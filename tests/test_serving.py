"""Session-serving tier (ISSUE 11 tentpole, r2d2_tpu/serving): the
SessionStore's LRU/reap/snapshot edge cases, the wire format's CRC
discipline, the continuous batcher's bucket shaping (bit-exact vs the
direct act fn, retrace-budgeted), the server's lifecycle/admission
behaviour over a real loopback socket, quantized-serving greedy parity,
restart-with-restore, and the load-gen acceptance e2e (hundreds of
concurrent sessions, accounting conserved, p99 on /metrics).

Everything runs tier-1-safe under ``JAX_PLATFORMS=cpu`` on the tiny
test-config geometry; waits poll with deadlines, never bare sleeps.
"""
import contextlib
import importlib.util
import json
import os
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from r2d2_tpu.actor import make_act_fn
from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.serving import (
    ContinuousBatcher,
    SessionClient,
    SessionServer,
    SessionStore,
    bucket_sizes,
)
from r2d2_tpu.serving.wire import (
    EMPTY_SPEC,
    FLAG_RESET,
    MSG_ACT,
    MSG_RSP,
    STATUS_GONE,
    STATUS_OK,
    STATUS_SHED,
    WireGarbled,
    decode_frame,
    encode_frame,
    peek_kind,
    session_request_spec,
)

A = 4


def _cfg(**kw):
    base = dict(serve_max_sessions=8, serve_max_batch=8,
                serve_session_idle_s=30.0)
    base.update(kw)
    return make_test_config(**base)


def _net_params(cfg, seed=0):
    net = create_network(cfg, A)
    return net, init_params(cfg, net, jax.random.PRNGKey(seed))


@contextlib.contextmanager
def _server(cfg, params, start=True):
    srv = SessionServer(cfg, A)
    srv.publish_params(params)
    if start:
        srv.start()
    try:
        yield srv
    finally:
        srv.stop()
        srv.close()


def _poll(predicate, budget=20.0, step=0.01, msg="condition"):
    """Poll-with-deadline (the test_chaos deflake pattern): never a bare
    sleep-then-assert."""
    deadline = time.time() + budget
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(step)
    raise AssertionError(f"timed out waiting for: {msg}")


def _assert_accounting(counts):
    assert counts["admitted"] == (counts["completed"] + counts["reaped"]
                                  + counts["evicted"] + counts["live"]), \
        counts


# ------------------------------------------------------------ SessionStore

def test_store_lru_eviction_order_respects_reuse():
    """LRU under reuse: touching (gathering for) a session moves it to
    the back of the eviction order, so the victim is the genuinely
    least-recently-used one."""
    store = SessionStore(_cfg(serve_max_sessions=3))
    for sid in (1, 2, 3):
        assert store.admit(sid)[0] == "ok"
    # touch 1: eviction order becomes 2, 3, 1
    store.gather([1], np.array([False]))
    verdict, victim = store.admit(4)
    assert (verdict, victim) == ("ok", 2)
    verdict, victim = store.admit(5)
    assert (verdict, victim) == ("ok", 3)
    assert store.counts()["evicted"] == 2
    _assert_accounting(store.counts())


def test_store_never_evicts_pending_sessions():
    """Evict-while-pending is the one corruption the store must never
    emit (the request would act on a zeroed slot): in-flight sessions
    are skipped by the LRU scan, and a store full of in-flight sessions
    sheds the admit instead."""
    store = SessionStore(_cfg(serve_max_sessions=2))
    assert store.admit(1)[0] == "ok"
    assert store.admit(2)[0] == "ok"
    assert store.mark_pending(1) and store.mark_pending(2)
    assert store.admit(3) == ("shed", None)          # nothing evictable
    store.clear_pending(2)
    # 1 is older but pinned; the scan must skip it and take 2
    assert store.admit(3) == ("ok", 2)
    assert store.mark_pending(1)                     # still live
    _assert_accounting(store.counts())


def test_store_idle_reap_vs_active_race():
    """The idle reaper must never take a session that is active (fresh
    last_used) or in flight (pending pin) — the race goes to the active
    side; a genuinely idle one goes."""
    store = SessionStore(_cfg(serve_max_sessions=4))
    for sid in (1, 2, 3):
        store.admit(sid, now=0.0)
    store.gather([1], np.array([False]), now=100.0)   # 1 is active
    store.mark_pending(2)                             # 2 is in flight
    reaped = store.reap_idle(10.0, now=101.0)
    assert reaped == [3]
    c = store.counts()
    assert c["reaped"] == 1 and c["live"] == 2
    _assert_accounting(c)
    # after the reply lands, 2 becomes reapable (1 is still fresh)
    store.clear_pending(2)
    assert store.reap_idle(10.0, now=105.0) == [2]
    assert store.counts()["live"] == 1
    _assert_accounting(store.counts())


def test_store_snapshot_restore_with_evicted_and_live_sessions():
    """Snapshot a store holding live sessions AND an eviction history;
    the restore must bring the hidden rows back bit-exact and carry the
    lifetime counters so the accounting invariant spans the restart."""
    cfg = _cfg(serve_max_sessions=2, lstm_layers=1, hidden_dim=16)
    store = SessionStore(cfg)
    rng = np.random.default_rng(0)
    store.admit(1)
    store.admit(2)
    h = rng.normal(size=(2, 2, cfg.lstm_layers, cfg.hidden_dim)
                   ).astype(np.float32)
    store.scatter([1, 2], h)
    assert store.admit(3) == ("ok", 1)   # evict 1; history now non-trivial
    store.scatter([3], h[:1] * 2.0)
    store.release(2, "completed")
    store.admit(4)
    snap = store.state()

    fresh = SessionStore(cfg)
    fresh.load_state(snap)
    assert fresh.counts() == store.counts()
    _assert_accounting(fresh.counts())
    # hidden rows bit-exact for the live sessions (3 carries its state)
    _, got = fresh.gather([3], np.array([False]))
    np.testing.assert_array_equal(got[0], h[0] * 2.0)
    # steps metadata survived too
    assert fresh.session_steps(3) == store.session_steps(3)
    # geometry mismatch fails loudly instead of loading garbage
    with pytest.raises(ValueError, match="does not match"):
        SessionStore(_cfg(serve_max_sessions=2, hidden_dim=32)
                     ).load_state(snap)


def test_store_reap_owner_and_adopt():
    store = SessionStore(_cfg())
    store.admit(1, owner=7)
    store.admit(2, owner=7)
    store.admit(3, owner=8)
    assert sorted(store.reap_owner(7)) == [1, 2]
    c = store.counts()
    assert c["reaped"] == 2 and c["live"] == 1
    # restored sessions are owner-less until adopted
    snap = store.state()
    fresh = SessionStore(_cfg())
    fresh.load_state(snap)
    assert fresh.reap_owner(8) == []     # old owner id means nothing now
    fresh.adopt(3, 9)
    assert fresh.reap_owner(9) == [3]
    _assert_accounting(fresh.counts())


# ------------------------------------------------------------- wire format

def test_wire_roundtrip_and_crc_gate():
    cfg = _cfg()
    spec = session_request_spec(cfg, A)
    rng = np.random.default_rng(1)
    obs = rng.integers(0, 256, cfg.stored_obs_shape).astype(np.uint8)
    la = rng.random(A).astype(np.float32)
    frame = encode_frame(spec, (MSG_ACT, 42, 7, FLAG_RESET),
                         dict(obs=obs, last_action=la,
                              last_reward=np.asarray([0.5], np.float32)))
    body = frame[4:]                      # strip the length word
    assert peek_kind(body) == MSG_ACT
    header, views = decode_frame(spec, body)
    assert header == (MSG_ACT, 42, 7, FLAG_RESET)
    np.testing.assert_array_equal(views["obs"], obs)
    np.testing.assert_array_equal(views["last_action"], la)
    assert views["last_reward"][0] == np.float32(0.5)
    # flip one payload byte AFTER the CRC landed: the gate must catch it
    garbled = bytearray(body)
    garbled[40] ^= 0xFF
    with pytest.raises(WireGarbled):
        decode_frame(spec, bytes(garbled))
    # a header garble (kind/session words) is caught too
    garbled = bytearray(body)
    garbled[0] ^= 0x01
    with pytest.raises(WireGarbled):
        decode_frame(spec, bytes(garbled))
    # payload-free frames round-trip as well
    f2 = encode_frame(EMPTY_SPEC, (MSG_RSP, 42, 7, STATUS_SHED))
    header, views = decode_frame(EMPTY_SPEC, f2[4:])
    assert header == (MSG_RSP, 42, 7, STATUS_SHED) and views == {}


# ---------------------------------------------------------------- batcher

def test_bucket_sizes_cover_and_cap():
    assert bucket_sizes(1) == (1,)
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(6) == (1, 2, 4, 6)
    assert bucket_sizes(256)[-1] == 256 and len(bucket_sizes(256)) == 9


def test_batcher_bucket_padding_bit_exact_and_retrace_budget():
    """The tier's core numeric invariant: a ragged batch served through
    bucket padding is BIT-EXACT vs the direct act fn on the exact rows
    (row-wise network math is batch-size independent), and driving every
    bucket stays inside the declared retrace budget."""
    from r2d2_tpu.utils.trace import RETRACES

    cfg = _cfg(serve_max_batch=8)
    net, params = _net_params(cfg)
    b = ContinuousBatcher(cfg, A)
    b.publish(params)
    # the REFERENCE fn is deliberately traced once per ragged size (the
    # very cost bucket shaping exists to avoid) — budget it accordingly
    act = make_act_fn(cfg, net, retrace_budget=8)
    rng = np.random.default_rng(0)
    for n in (1, 3, 5, 8):
        obs = rng.integers(0, 256,
                           (n, *cfg.stored_obs_shape)).astype(np.uint8)
        la = rng.random((n, A)).astype(np.float32)
        lr = rng.random(n).astype(np.float32)
        h = (rng.normal(size=(n, 2, cfg.lstm_layers, cfg.hidden_dim))
             * 0.1).astype(np.float32)
        q1, h1 = b.act(obs, la, lr, h)
        q2, h2 = act(params, obs, la, lr, h)
        np.testing.assert_array_equal(q1, np.asarray(q2))
        np.testing.assert_array_equal(h1, np.asarray(h2))
    with pytest.raises(ValueError, match="exceeds serve_max_batch"):
        b.bucket(9)
    RETRACES.assert_within_budgets()


def test_batcher_act_under_armed_transfer_guard():
    """The serve path's declared-transfer contract, JAX-enforced (r19):
    after warm-up, ``act()`` runs inside ``disallow("serving.act")`` —
    the padded-scratch H2D rides the ``serving.act_put`` allow span and
    the ONE result fetch is an explicit ``jax.device_get`` inside
    ``serving.act_fetch``.  Results stay bit-exact vs the unarmed path,
    one fetch per batch regardless of ragged size, zero trips."""
    from r2d2_tpu.utils.trace import HOST_TRANSFERS, TRANSFER_GUARD

    cfg = _cfg(serve_max_batch=8)
    net, params = _net_params(cfg)
    b = ContinuousBatcher(cfg, A)
    b.publish(params)
    b.warmup()  # every bucket compiled before arming

    rng = np.random.default_rng(7)
    batches = []
    for n in (1, 3, 8):
        batches.append((
            rng.integers(0, 256,
                         (n, *cfg.stored_obs_shape)).astype(np.uint8),
            rng.random((n, A)).astype(np.float32),
            rng.random(n).astype(np.float32),
            (rng.normal(size=(n, 2, cfg.lstm_layers, cfg.hidden_dim))
             * 0.1).astype(np.float32)))
    unarmed = [b.act(*args) for args in batches]

    fetch0 = HOST_TRANSFERS.get("serving.act_fetch")
    with TRANSFER_GUARD.arm():
        armed = [b.act(*args) for args in batches]
    assert HOST_TRANSFERS.get("serving.act_fetch") - fetch0 \
        == len(batches)
    snap = TRANSFER_GUARD.snapshot()
    assert snap.get("trip.serving.act", 0) == 0, snap
    assert snap.get("window.serving.act", 0) >= len(batches)
    for (q1, h1), (q2, h2) in zip(unarmed, armed):
        np.testing.assert_array_equal(q1, q2)
        np.testing.assert_array_equal(h1, h2)


def test_serve_dtype_bf16_quantizes_with_greedy_parity():
    """QuaRL gate (the param_pump_dtype pattern on the serving tier):
    bf16 publish must actually quantize (params differ) while greedy
    actions on a pinned request stream match float32 exactly."""
    cfg32 = _cfg(serve_max_batch=8)
    cfg16 = _cfg(serve_max_batch=8, serve_dtype="bfloat16")
    _, params = _net_params(cfg32)
    b32 = ContinuousBatcher(cfg32, A)
    b32.publish(params)
    b16 = ContinuousBatcher(cfg16, A)
    b16.publish(params)
    # the quantization is real: at least one leaf changed
    l32 = jax.tree.leaves(b32._params)
    l16 = jax.tree.leaves(b16._params)
    assert any(not np.array_equal(np.asarray(a), np.asarray(x))
               for a, x in zip(l32, l16))
    rng = np.random.default_rng(7)
    n = 8
    obs = rng.integers(0, 256, (n, *cfg32.stored_obs_shape)
                       ).astype(np.uint8)
    la = np.zeros((n, A), np.float32)
    lr = np.zeros(n, np.float32)
    h = (rng.normal(size=(n, 2, cfg32.lstm_layers, cfg32.hidden_dim))
         * 0.1).astype(np.float32)
    q32, _ = b32.act(obs, la, lr, h)
    q16, _ = b16.act(obs, la, lr, h)
    np.testing.assert_allclose(q32, q16, atol=5e-2, rtol=5e-2)
    np.testing.assert_array_equal(q32.argmax(axis=1), q16.argmax(axis=1))


# ------------------------------------------------------------------ server

def test_server_sessions_bit_exact_vs_local_act():
    """Two interleaved sessions driven over the real socket must produce
    the exact q stream a client-side unrolled act fn produces — the
    session-resident hidden is carried server-side bit-exact, episode
    resets included."""
    cfg = _cfg()
    net, params = _net_params(cfg)
    act = make_act_fn(cfg, net)
    rng = np.random.default_rng(3)
    steps = 6
    streams = {sid: [rng.integers(0, 256, cfg.stored_obs_shape
                                  ).astype(np.uint8) for _ in range(steps)]
               for sid in (1, 2)}
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        try:
            ref_hidden = {sid: np.zeros(
                (1, 2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
                for sid in (1, 2)}
            la = {sid: np.zeros(A, np.float32) for sid in (1, 2)}
            assert cl.open_session(1) == STATUS_OK
            assert cl.open_session(2) == STATUS_OK
            for t in range(steps):
                for sid in (1, 2):
                    obs = streams[sid][t]
                    st, q = cl.act(sid, obs, la[sid], 0.125 * t,
                                   reset=t == 0)
                    assert st == STATUS_OK
                    if t == 0:
                        ref_hidden[sid][:] = 0.0
                    qr, hr = act(params, obs[None], la[sid][None],
                                 np.asarray([0.125 * t], np.float32),
                                 ref_hidden[sid])
                    np.testing.assert_array_equal(q, np.asarray(qr)[0])
                    ref_hidden[sid] = np.asarray(hr)
                    la[sid] = np.zeros(A, np.float32)
                    la[sid][int(np.argmax(q))] = 1.0
            # the server-resident hidden equals the client-side unroll
            _, got = srv.store.gather([1, 2], np.array([False, False]))
            np.testing.assert_array_equal(got[0], ref_hidden[1][0])
            np.testing.assert_array_equal(got[1], ref_hidden[2][0])
            assert cl.close_session(1) == STATUS_OK
            assert cl.close_session(2) == STATUS_OK
        finally:
            cl.close()
        _assert_accounting(srv.store.counts())


def test_server_eviction_answers_gone_then_reopen():
    """LRU eviction under a budget of 1: the evicted session's next act
    answers STATUS_GONE (never an act on a zeroed slot); a re-open
    readmits it fresh."""
    cfg = _cfg(serve_max_sessions=1)
    _, params = _net_params(cfg)
    obs = np.zeros(cfg.stored_obs_shape, np.uint8)
    la = np.zeros(A, np.float32)
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        try:
            assert cl.open_session(1) == STATUS_OK
            st, _ = cl.act(1, obs, la, 0.0, reset=True)
            assert st == STATUS_OK
            assert cl.open_session(2) == STATUS_OK    # evicts idle 1
            st, _ = cl.act(1, obs, la, 0.0)
            assert st == STATUS_GONE
            assert cl.open_session(1) == STATUS_OK    # evicts 2, readmits
            st, _ = cl.act(1, obs, la, 0.0, reset=True)
            assert st == STATUS_OK
            c = srv.store.counts()
            assert c["evicted"] == 2
            _assert_accounting(c)
            assert srv.registry.get_counter("serving.gone") >= 1
        finally:
            cl.close()


def test_server_bounded_queue_sheds_429():
    """The bounded pending queue: with the batch loop held still and
    serve_pending_max=1, a second pipelined act sheds IMMEDIATELY with
    STATUS_SHED (counted in serving.rejected) — the client never waits
    on a queue that cannot drain."""
    cfg = _cfg(serve_pending_max=1)
    _, params = _net_params(cfg)
    with _server(cfg, params, start=False) as srv:
        # readers only — serve_once is driven by hand, so the queue
        # genuinely backs up
        srv._started = True
        srv.supervisor.start("session_accept", srv._accept_loop)
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        try:
            assert cl.open_session(1) == STATUS_OK
            assert cl.open_session(2) == STATUS_OK
            obs = np.zeros(cfg.stored_obs_shape, np.uint8)
            la = np.zeros(A, np.float32)
            s1 = cl.send_act(1, obs, la, 0.0, reset=True)
            s2 = cl.send_act(2, obs, la, 0.0, reset=True)
            # the second act overflows the bound and sheds now
            st2, _ = cl.recv(2, s2)
            assert st2 == STATUS_SHED
            assert srv.registry.get_counter("serving.rejected") == 1
            # the queued one serves once the batch loop turns
            assert srv.serve_once(idle_sleep=0.0) == 1
            st1, q = cl.recv(1, s1)
            assert st1 == STATUS_OK and q is not None
            assert srv.healthz()["status"] == "degraded"   # shed window
        finally:
            cl.close()


def test_server_disconnect_reaps_sessions():
    """kill_session_client shape: an abrupt disconnect mid-episode must
    reap every session the connection owned — hidden slots never leak."""
    cfg = _cfg()
    _, params = _net_params(cfg)
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        assert cl.open_session(1) == STATUS_OK
        assert cl.open_session(2) == STATUS_OK
        obs = np.zeros(cfg.stored_obs_shape, np.uint8)
        la = np.zeros(A, np.float32)
        st, _ = cl.act(1, obs, la, 0.0, reset=True)
        assert st == STATUS_OK
        cl.abandon()
        _poll(lambda: srv.store.counts()["reaped"] == 2,
              msg="disconnect reap")
        c = srv.store.counts()
        assert c["live"] == 0
        _assert_accounting(c)
        assert srv.healthz()["status"] in ("ok", "degraded")


def test_server_idle_reap_frees_abandoned_sessions():
    cfg = _cfg(serve_session_idle_s=0.2)
    _, params = _net_params(cfg)
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        try:
            assert cl.open_session(1) == STATUS_OK
            obs = np.zeros(cfg.stored_obs_shape, np.uint8)
            st, _ = cl.act(1, obs, np.zeros(A, np.float32), 0.0,
                           reset=True)
            assert st == STATUS_OK
            # stop sending; the reaper must claim the session (the
            # connection stays open — idle, not disconnected)
            _poll(lambda: srv.store.counts()["reaped"] == 1,
                  msg="idle reap")
            _assert_accounting(srv.store.counts())
        finally:
            cl.close()


def test_server_restart_restores_sessions_bit_exact(tmp_path):
    """Restart-with-restore: k steps, snapshot through the Checkpointer,
    a FRESH server restores, the client reconnects and continues by
    session id — the q stream must equal an uninterrupted run's."""
    cfg = _cfg()
    _, params = _net_params(cfg)
    rng = np.random.default_rng(5)
    steps = 8
    stream = [rng.integers(0, 256, cfg.stored_obs_shape).astype(np.uint8)
              for _ in range(steps)]
    la = np.zeros(A, np.float32)

    def drive(cl, lo, hi, last_action):
        out = []
        for t in range(lo, hi):
            st, q = cl.act(1, stream[t], last_action, 0.0, reset=t == 0)
            assert st == STATUS_OK
            out.append(np.array(q))
            last_action = np.zeros(A, np.float32)
            last_action[int(np.argmax(q))] = 1.0
        return out, last_action

    # uninterrupted reference
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        assert cl.open_session(1) == STATUS_OK
        want, _ = drive(cl, 0, steps, la)
        cl.close()

    # interrupted: serve, snapshot at the midpoint, restore, continue
    ckpt = Checkpointer(str(tmp_path))
    with _server(cfg, params) as srv:
        cl = SessionClient(cfg, A, srv.host, srv.port, timeout=30)
        assert cl.open_session(1) == STATUS_OK
        got, la_mid = drive(cl, 0, steps // 2, la)
        # shutdown order matters: stop FIRST so the connection teardown
        # is a server shutdown (sessions survive into the snapshot), not
        # a client abandon (which would reap them)
        srv.stop()
        srv.close()
        cl.close()
        meta = srv.save_sessions(ckpt)
        assert meta["live"] == 1
    with _server(cfg, params, start=False) as srv2:
        assert srv2.restore_sessions(ckpt)
        srv2.start()
        cl = SessionClient(cfg, A, srv2.host, srv2.port, timeout=30)
        more, _ = drive(cl, steps // 2, steps, la_mid)
        got += more
        cl.close()
        # a reconnect binds the restored session to the new connection,
        # so its disconnect reaps normally (no leaked slot)
        _poll(lambda: srv2.store.counts()["live"] == 0,
              msg="restored session reaped on disconnect")
        _assert_accounting(srv2.store.counts())
    assert len(got) == len(want)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)

    # no snapshot at all → clean cold start
    empty = Checkpointer(str(tmp_path / "empty"))
    with _server(cfg, params, start=False) as srv3:
        assert not srv3.restore_sessions(empty)


# ----------------------------------------------------------- chaos kinds

def test_session_chaos_kinds_parse_and_fire():
    from r2d2_tpu.utils.chaos import ChaosInjector, parse_spec

    spec = "kill_session_client:at=2;slow_session_client:at=1,dur=0.5"
    assert set(parse_spec(spec)) == {"kill_session_client",
                                     "slow_session_client"}
    chaos = ChaosInjector(spec)
    assert chaos.session_client_slow_seconds() == 0.5
    assert chaos.session_client_slow_seconds() == 0.0   # at=1: once
    assert not chaos.session_client_kill()
    assert chaos.session_client_kill()                  # opportunity 2
    assert not chaos.session_client_kill()
    # config validation accepts the new kinds
    make_test_config(chaos_spec=spec)


# ------------------------------------------------------------- validation

def test_serve_config_validation():
    for bad in (dict(serve_dtype="int8"), dict(serve_max_sessions=0),
                dict(serve_max_batch=0), dict(serve_session_idle_s=0.0),
                dict(serve_pending_max=0),
                dict(serve_request_deadline=0.0),
                dict(serve_port=65536)):
        with pytest.raises(ValueError):
            make_test_config(**bad)
    cfg = make_test_config(serve_dtype="bfloat16", serve_port=-1)
    assert cfg.serve_dtype == "bfloat16"


def test_cli_serve_parser():
    from r2d2_tpu.cli import main

    # serve without --ckpt-dir fails loudly at the parser
    with pytest.raises(SystemExit):
        main(["serve", "--preset", "test", "--game", "Fake"])


def test_checkpointer_session_snapshot_roundtrip(tmp_path):
    ckpt = Checkpointer(str(tmp_path))
    assert ckpt.restore_sessions() is None

    def writer(path):
        with open(path, "wb") as f:
            f.write(b"payload")
        return dict(live=3)

    meta = ckpt.save_sessions(writer)
    assert meta["live"] == 3
    got, payload = ckpt.restore_sessions()
    assert got["live"] == 3
    with open(payload, "rb") as f:
        assert f.read() == b"payload"
    # overwrite: the second save replaces the first, no .old left behind
    def writer2(path):
        with open(path, "wb") as f:
            f.write(b"payload2")
        return dict(live=4)

    assert ckpt.save_sessions(writer2)["live"] == 4
    assert ckpt.restore_sessions()[0]["live"] == 4
    assert not os.path.isdir(ckpt._sessions_path() + ".old")
    # crash-between-renames shape: only the .old snapshot exists —
    # restore must fall back to it, never come up empty
    os.replace(ckpt._sessions_path(), ckpt._sessions_path() + ".old")
    got, payload = ckpt.restore_sessions()
    assert got["live"] == 4 and payload.endswith("sessions.bin")
    os.replace(ckpt._sessions_path() + ".old", ckpt._sessions_path())
    # a torn snapshot (no meta.json) is never selected
    os.remove(os.path.join(ckpt._sessions_path(), "meta.json"))
    assert ckpt.restore_sessions() is None


# ------------------------------------------------------------- acceptance

# slow: ~20 s 200-session run on the tier-1 wall budget (ISSUE 15
# rebalance).  Tier-1 keeps the bit-exact server-vs-local socket test,
# eviction/reap/admission units and the wire layer; the committed
# session soak (chaos_soak --sessions) covers the full-load composition.
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_acceptance_200_sessions_end_to_end():
    """The ISSUE's load-gen acceptance: >= 200 concurrent synthetic
    sessions through the tier under an LRU budget that FORCES evictions,
    zero unbounded waits (every client call deadline-bounded), the
    accounting invariant conserved, and the p99 act latency visible on
    /metrics."""
    spec = importlib.util.spec_from_file_location(
        "session_load_gen",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "session_load_gen.py"))
    slg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(slg)

    cfg = _cfg(serve_max_sessions=128, serve_max_batch=32,
               serve_session_idle_s=20.0)
    _, params = _net_params(cfg)
    with _server(cfg, params, start=False) as srv:
        for name, loop in srv.exporter_loops(-1):
            srv.supervisor.start(name, loop)
        srv.start()
        summary = slg.run_load(cfg, A, srv.host, srv.port, sessions=200,
                               workers=4, steps_mean=6, think_s=0.0,
                               run_seconds=120.0, seed=3)
        assert not summary["workers_failed"]
        assert summary["completed"] > 0 and summary["acts"] > 200
        # the budget (128 < 200) really forced the LRU path
        c = srv.store.counts()
        assert c["evicted"] > 0
        _assert_accounting(c)
        # every admitted session left through a counted exit: the
        # client saw the evictions as GONE and retired those sessions
        assert summary["completed"] + summary["gone"] \
            + summary["abandoned"] <= c["admitted"]
        assert srv.healthz()["status"] in ("ok", "degraded")
        # p99 act latency reported through /metrics (histogram + gauge)
        _poll(lambda: srv.registry.get_gauge("serving.act_latency_p99_s")
              is not None, msg="p99 gauge")
        port = srv.exporter.port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "r2d2_serving_act_latency_s_bucket" in body
        assert "r2d2_serving_act_latency_p99_s" in body
        assert "r2d2_serving_batch_size_bucket" in body
        # and the three-state healthz contract answers over HTTP
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["status"] in ("ok", "degraded")
        # continuous batching genuinely coalesced ragged requests
        assert srv.stats()["mean_batch"] > 1.0
