"""Device-resident PER (cfg.in_graph_per): sampling, IS weights, and
priority feedback inside the super-step.

Covers the redesign of the reference's host-side sum-tree feedback loop
(worker.py:242-276 update, worker.py:300-316 staging lag): the sampling
distribution and index arithmetic must match the host path exactly, the
in-graph scatter must only touch sampled leaves, and the full fabric must
run with zero host priority traffic.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.learner.step import _in_graph_sample, create_train_state
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.parallel.mesh import trivial_mesh
from r2d2_tpu.parallel.sharding import (
    ShardingTable, pjit_in_graph_per_super_step)
from r2d2_tpu.replay.block import LocalBuffer
from r2d2_tpu.replay.device_ring import DeviceRing
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.envs.fake import FakeAtariEnv

A = 4


def make_cfg(**kw):
    return make_test_config(device_replay=True, in_graph_per=True, **kw)


def ig_step(cfg, net, k, state):
    """The unified device-PER super-step on a trivial 1-device mesh — the
    single-device oracle of the same (only) entry point."""
    return pjit_in_graph_per_super_step(
        cfg, net, ShardingTable(trivial_mesh(), cfg), k,
        state_template=state)


def scripted_blocks(cfg, n_blocks, seed=0):
    rng = np.random.default_rng(seed)
    local = LocalBuffer(cfg, A)
    out = []
    obs = rng.integers(0, 256, cfg.stored_obs_shape, np.uint8)
    local.reset(obs)
    while len(out) < n_blocks:
        for _ in range(cfg.block_length):
            obs = rng.integers(0, 256, cfg.stored_obs_shape, np.uint8)
            q = rng.normal(size=A).astype(np.float32)
            hidden = rng.normal(size=(2, cfg.lstm_layers,
                                      cfg.hidden_dim)).astype(np.float32)
            local.add(int(rng.integers(A)), float(rng.normal()), obs, q,
                      hidden)
        blk, prios, _ = local.finish(rng.normal(size=A).astype(np.float32))
        out.append((blk, prios))
    return out


def filled(cfg, n_blocks=4, seed=0):
    ring = DeviceRing(cfg, A)
    buf = ReplayBuffer(cfg, A, rng=np.random.default_rng(99),
                       device_ring=ring)
    for blk, prios in scripted_blocks(cfg, n_blocks, seed):
        buf.add(blk, prios, None)
    return buf, ring


def test_per_leaves_mirror_host_tree_values():
    """commit_per must store exactly what the host tree would: td**alpha
    at the block's real sequences, zero (unsampleable) past them."""
    cfg = make_cfg()
    K = cfg.seqs_per_block
    buf, ring = filled(cfg, n_blocks=3)
    host = ReplayBuffer(cfg.replace(in_graph_per=False, device_replay=False),
                        A, rng=np.random.default_rng(99))
    for blk, prios in scripted_blocks(cfg, 3):
        host.add(blk, prios, None)

    dev_p = np.asarray(ring.take_prios())
    leaves = host.tree.nodes[host.tree.leaf_offset:
                             host.tree.leaf_offset + cfg.num_blocks * K]
    np.testing.assert_allclose(dev_p, leaves[:dev_p.size], rtol=1e-6)
    # the host tree behind the in-graph buffer stays untouched
    assert buf.tree.nodes.sum() == 0.0


def test_in_graph_sample_matches_host_index_arithmetic():
    """Sampled ints bundles must reproduce sample_meta's arithmetic
    (replay_buffer.py:372-390) and IS weights the reference formula on
    exact densities; zero-priority leaves are never sampled."""
    cfg = make_cfg()
    K, L = cfg.seqs_per_block, cfg.learning_steps
    buf, ring = filled(cfg, n_blocks=3)

    prios = np.asarray(ring.take_prios())
    meta = {k: np.asarray(v) for k, v in ring.per_meta().items()}
    idx, w, ints = jax.jit(
        lambda key, p, sm, fb: _in_graph_sample(cfg, key, p, sm, fb),
    )(jax.random.PRNGKey(3), prios, meta["seq_meta"], meta["first"])
    idx, w, ints = map(np.asarray, (idx, w, ints))

    assert (prios[idx] > 0).all()
    block_idx, seq_idx = idx // K, idx % K
    burn = buf.burn_in_steps[block_idx, seq_idx]
    start = buf.first_burn_in[block_idx] + seq_idx * L
    expected = np.stack(
        [block_idx, start - burn, seq_idx, burn,
         buf.learning_steps[block_idx, seq_idx],
         buf.forward_steps[block_idx, seq_idx]], axis=1)
    np.testing.assert_array_equal(ints, expected)

    q = prios[idx] / prios.sum()
    np.testing.assert_allclose(
        w, (q / q.min()) ** (-cfg.importance_sampling_exponent),
        rtol=1e-5)


def test_partial_block_add_keeps_padding_unsampleable():
    """A short episode's partial block (num_sequences < K) must commit
    cleanly — priorities arrive K-length zero-padded (block.py:108) and
    the padding stays zero on device."""
    cfg = make_cfg()
    K = cfg.seqs_per_block
    ring = DeviceRing(cfg, A)
    buf = ReplayBuffer(cfg, A, rng=np.random.default_rng(1),
                       device_ring=ring)
    rng = np.random.default_rng(5)
    local = LocalBuffer(cfg, A)
    local.reset(rng.integers(0, 256, cfg.stored_obs_shape, np.uint8))
    for _ in range(max(1, cfg.block_length // 2 - 1)):
        local.add(int(rng.integers(A)), 0.5,
                  rng.integers(0, 256, cfg.stored_obs_shape, np.uint8),
                  rng.normal(size=A).astype(np.float32),
                  rng.normal(size=(2, cfg.lstm_layers,
                                   cfg.hidden_dim)).astype(np.float32))
    blk, prios, _ = local.finish(None)  # episode end -> partial block
    assert blk.num_sequences < K
    buf.add(blk, prios, 1.0)
    dev_p = np.asarray(ring.take_prios())
    assert (dev_p[blk.num_sequences:K] == 0).all()
    assert (dev_p[:blk.num_sequences] > 0).any()


def test_in_graph_sampling_distribution_is_proportional():
    """Empirical draw frequencies track priorities (the sum-tree's
    proportional contract) within sampling noise."""
    cfg = make_cfg()
    buf, ring = filled(cfg, n_blocks=3)
    prios = np.asarray(ring.take_prios())
    meta = ring.per_meta()
    pj = jnp.asarray(prios)
    f = jax.jit(lambda key: _in_graph_sample(cfg, key, pj,
                                             meta["seq_meta"],
                                             meta["first"])[0])
    counts = np.zeros(prios.size)
    draws = 400
    for s in range(draws):
        np.add.at(counts, np.asarray(f(jax.random.PRNGKey(s))), 1)
    expect = prios / prios.sum() * counts.sum()
    live = expect > 20  # only well-populated bins are statistically firm
    assert live.any()
    np.testing.assert_allclose(counts[live], expect[live], rtol=0.35)
    assert counts[prios == 0].sum() == 0


def test_in_graph_super_step_trains_and_scatters_feedback():
    cfg = make_cfg(superstep_k=2)
    buf, ring = filled(cfg, n_blocks=3)
    net = create_network(cfg, A)
    state = create_train_state(cfg, init_params(cfg, net,
                                                jax.random.PRNGKey(0)))
    p0 = np.asarray(ring.take_prios())
    meta = ring.per_meta()
    step0 = int(state.step)
    fn = ig_step(cfg, net, 2, state)
    state2, new_prios, losses = fn(state, ring.snapshot(),
                                   ring.take_prios(), meta["seq_meta"],
                                   meta["first"], jnp.asarray(7, jnp.uint32))
    losses = np.asarray(losses)
    assert losses.shape == (2,) and np.isfinite(losses).all()
    assert int(state2.step) == step0 + 2
    p1 = np.asarray(new_prios)
    changed = np.nonzero(p1 != p0)[0]
    assert changed.size > 0, "no priority feedback scattered"
    assert (p0[changed] > 0).all(), "scatter touched an invalid leaf"
    assert (p1[changed] >= 0).all()
    # padding/empty leaves stay unsampleable
    assert (p1[p0 == 0] == 0).all()


@pytest.mark.slow
def test_in_graph_scatter_writes_host_equivalent_priorities():
    """The in-scan priority scatter must write exactly what the host
    feedback path would: td**alpha of the mixed-TD priorities the train
    step computes for the same sampled batch.  Cross-checked by
    replaying the (deterministic) stratified draw on the host and
    running the plain train step on the identically gathered batch."""
    from r2d2_tpu.parallel.sharding import pjit_train_step
    from r2d2_tpu.replay.device_ring import gather_batch

    cfg = make_cfg(superstep_k=1)
    buf, ring = filled(cfg, n_blocks=3)
    net = create_network(cfg, A)
    state = create_train_state(cfg, init_params(cfg, net,
                                                jax.random.PRNGKey(0)))
    meta = ring.per_meta()
    p0 = jnp.asarray(np.asarray(ring.take_prios()))
    dispatch_idx = jnp.asarray(3, jnp.uint32)

    # replay the super-step's exact key schedule for k=1, step 0
    key0 = jax.random.split(
        jax.random.fold_in(jax.random.PRNGKey(cfg.seed), dispatch_idx),
        1)[0]
    idx, w, ints = map(np.asarray, _in_graph_sample(
        cfg, key0, p0, meta["seq_meta"], meta["first"]))

    # plain train step on the identically gathered batch
    batch = gather_batch(cfg, ring.snapshot(), jnp.asarray(ints),
                         jnp.asarray(w))
    _, _, prios_ref = pjit_train_step(cfg, net, state_template=state)(
        state, batch)

    # the in-graph super-step (fresh state: the first one was donated;
    # snapshot p0 to host BEFORE the call donates it)
    p0_np = np.asarray(p0).copy()
    state2 = create_train_state(cfg, init_params(cfg, net,
                                                 jax.random.PRNGKey(0)))
    fn = ig_step(cfg, net, 1, state2)
    _, new_prios, _ = fn(state2, ring.snapshot(), p0, meta["seq_meta"],
                         meta["first"], dispatch_idx)

    expected = p0_np
    expected[idx] = np.asarray(prios_ref) ** cfg.prio_exponent
    np.testing.assert_allclose(np.asarray(new_prios), expected,
                               rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_in_graph_per_sharded_matches_single_device():
    """dp=8 mesh device-PER super-step == single-device: same losses,
    same scattered priorities, same params (sampling is deterministic
    given the fold_in key, so the mesh run draws identical strata)."""
    from r2d2_tpu.parallel.mesh import make_mesh

    cfg = make_cfg(superstep_k=2)
    buf, ring = filled(cfg, n_blocks=3)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    meta = ring.per_meta()
    p_start = np.asarray(ring.take_prios())
    idx7 = jnp.asarray(7, jnp.uint32)

    s0 = create_train_state(cfg, params)
    s1, p1, l1 = ig_step(cfg, net, 2, s0)(
        s0, ring.snapshot(),
        jnp.asarray(p_start), meta["seq_meta"], meta["first"], idx7)

    table = ShardingTable(make_mesh(cfg), cfg)
    sN0 = create_train_state(cfg, params)
    stepN = pjit_in_graph_per_super_step(cfg, net, table, 2,
                                         state_template=sN0)
    sN, pN, lN = stepN(
        table.place_state(sN0),
        ring.snapshot(), jnp.asarray(p_start), meta["seq_meta"],
        meta["first"], idx7)

    np.testing.assert_allclose(np.asarray(l1), np.asarray(lN), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                               rtol=1e-4, atol=1e-7)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


import pytest


@pytest.mark.parametrize("fused", [False, True])
@pytest.mark.slow
def test_train_end_to_end_in_graph_per(fused):
    """Full threaded fabric with device PER, at both loss paths (the
    default two-unroll and the fused double unroll — orthogonal
    features: sampling plane vs loss path): updates advance, losses are
    finite, and the log plane's counters stay live through note_updates
    (priority feedback never crosses the host)."""
    from r2d2_tpu.train import train

    cfg = make_cfg(game_name="Fake", superstep_k=2, training_steps=8,
                   fused_double_unroll=fused, log_interval=0.2)
    metrics = train(
        cfg,
        env_factory=lambda c, seed: FakeAtariEnv(
            obs_shape=c.stored_obs_shape, action_dim=A, seed=seed),
        verbose=False)
    assert metrics["num_updates"] >= cfg.training_steps
    assert np.isfinite(metrics["mean_loss"])
    assert metrics["buffer_training_steps"] == metrics["num_updates"]
    assert not metrics["fabric_failed"]


def test_compensated_cumsum_matches_f64():
    """_compensated_cumsum's f32 prefixes must agree with a float64
    oracle at stratum-boundary resolution across flagship-scale leaf
    arrays — the host SumTree accumulates in f64 (replay/sum_tree.py),
    and a plain f32 cumsum drifts enough to shift boundaries."""
    from r2d2_tpu.learner.step import _compensated_cumsum

    fn = jax.jit(_compensated_cumsum)
    diffs = plain_diffs = 0
    for seed in range(8):
        rng = np.random.default_rng(seed)
        x = (rng.random(50_000) * rng.exponential(1, 50_000)).astype(
            np.float32)
        x[rng.random(50_000) < 0.3] = 0.0   # padding slots
        ref = np.cumsum(x.astype(np.float64))
        hi = np.asarray(fn(jnp.asarray(x)))
        u = rng.random(64)
        t64 = (np.arange(64) + u) * (ref[-1] / 64)
        t32 = ((np.arange(64, dtype=np.float32) + u.astype(np.float32))
               * (hi[-1].astype(np.float32) / np.float32(64)))
        diffs += int(np.sum(np.searchsorted(ref, t64, side="right")
                            != np.searchsorted(hi, t32, side="right")))
        plain_diffs += int(np.sum(
            np.searchsorted(ref, t64, side="right")
            != np.searchsorted(np.cumsum(x), t32, side="right")))
    assert diffs == 0
    assert plain_diffs > 0  # the plain-f32 drift this guards against


def test_compensated_cumsum_adversarial_spread_per_slab():
    """The in-graph sampler's worst case (VERDICT r5 #7): the largest
    per-slab leaf count a v5e ring supports, under adversarial mixed
    priority spreads (1e-6 leaves sprinkled among 1e3 leaves, with
    padding zeros) — 0 stratum disagreements vs the f64 oracle.

    A plain f32 cumsum accumulates O(n·eps·total) drift here (~5
    absolute at these magnitudes), swallowing the tiny leaves' mass and
    shifting large-leaf boundaries; the compensated scan must hold every
    stratum boundary at oracle resolution."""
    from r2d2_tpu.config import pong_config
    from r2d2_tpu.learner.step import _compensated_cumsum
    from r2d2_tpu.replay.replay_buffer import data_bytes

    # leaf capacity of one v5e chip (16 GB HBM, 80% budget — the ring
    # guard's own threshold) at flagship Pong shapes: ~40k leaves/slab
    cfg = pong_config()
    per_block = data_bytes(cfg, 6) // cfg.num_blocks
    n_blocks = int(0.8 * 16e9) // per_block
    N = int(n_blocks * cfg.seqs_per_block)
    assert N >= 30_000  # sanity: flagship scale, not a toy

    fn = jax.jit(_compensated_cumsum)
    diffs = 0
    for seed in range(8):
        rng = np.random.default_rng(100 + seed)
        x = np.full(N, 1e-6, np.float32)      # near-converged TD errors
        x[rng.random(N) < 0.05] = 1e3         # fresh high-surprise blocks
        x[rng.random(N) < 0.3] = 0.0          # padding / empty slots
        ref = np.cumsum(x.astype(np.float64))
        hi = np.asarray(fn(jnp.asarray(x)))
        u = rng.random(64)                    # one stratum per batch row
        t64 = (np.arange(64) + u) * (ref[-1] / 64)
        t32 = ((np.arange(64, dtype=np.float32) + u.astype(np.float32))
               * (hi[-1].astype(np.float32) / np.float32(64)))
        diffs += int(np.sum(np.searchsorted(ref, t64, side="right")
                            != np.searchsorted(hi, t32, side="right")))
    assert diffs == 0


def dp_filled(cfg, n_blocks=8, seed=0):
    """A dp-layout ring + buffer with every slab populated."""
    from r2d2_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(cfg)
    ring = DeviceRing(cfg, A, table=ShardingTable(mesh, cfg), layout="dp")
    buf = ReplayBuffer(cfg, A, rng=np.random.default_rng(99),
                       device_ring=ring)
    for blk, prios in scripted_blocks(cfg, n_blocks, seed):
        buf.add(blk, prios, None)
    return mesh, buf, ring


def test_in_graph_sample_raw_matches_host_per_slab():
    """The grouped sampler's building block (_in_graph_sample_raw) on
    each dp slab: indices stay slab-local and positive-priority, the
    ints bundle reproduces the host arithmetic for the slab's physical
    slots, and densities are exactly prio/mass_slab — the host
    _grouped_densities contract (replay_buffer.py)."""
    from r2d2_tpu.learner.step import _in_graph_sample_raw

    cfg = make_cfg(mesh_shape=(("dp", 4), ("tp", 2)),
                   device_ring_layout="dp")
    K, L = cfg.seqs_per_block, cfg.learning_steps
    mesh, buf, ring = dp_filled(cfg)
    G, bpg = ring.num_groups, ring.blocks_per_group
    S, Bg = bpg * K, cfg.batch_size // G
    prios = np.asarray(ring.take_prios())
    meta = {k: np.asarray(v) for k, v in ring.per_meta().items()}
    assert buf.ready or buf.size < cfg.learning_starts

    fn = jax.jit(lambda key, p, sm, fb: _in_graph_sample_raw(
        cfg, key, p, sm, fb, Bg))
    for g in range(G):
        p_g = prios[g * S:(g + 1) * S]
        assert p_g.sum() > 0, "fixture must populate every slab"
        idx, q, ints = map(np.asarray, fn(
            jax.random.PRNGKey(g), p_g,
            meta["seq_meta"][g * bpg:(g + 1) * bpg],
            meta["first"][g * bpg:(g + 1) * bpg]))
        assert (idx >= 0).all() and (idx < S).all()
        assert (p_g[idx] > 0).all()
        blk_l, seq_idx = idx // K, idx % K
        blk_phys = g * bpg + blk_l          # physical slot in the ring
        burn = buf.burn_in_steps[blk_phys, seq_idx]
        start = buf.first_burn_in[blk_phys] + seq_idx * L
        expected = np.stack(
            [blk_l, start - burn, seq_idx, burn,
             buf.learning_steps[blk_phys, seq_idx],
             buf.forward_steps[blk_phys, seq_idx]], axis=1)
        np.testing.assert_array_equal(ints, expected)
        np.testing.assert_allclose(q, p_g[idx] / p_g.sum(), rtol=1e-5)


@pytest.mark.slow
def test_in_graph_per_dp_super_step_trains_and_guards_padding():
    """The dp-layout device-PER super-step (the SAME table-driven pjit
    step — PER leaves shard with the ring slabs, the stratified draw is
    global under GSPMD): finite losses, params advance, and the priority
    scatter can only touch positive leaves — zero (padding / empty-slot)
    leaves stay exactly zero, so padding never becomes sampleable."""
    cfg = make_cfg(superstep_k=2, mesh_shape=(("dp", 4), ("tp", 2)),
                   device_ring_layout="dp")
    mesh, buf, ring = dp_filled(cfg, n_blocks=6)  # some slots stay empty
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    table = ShardingTable(mesh, cfg)
    state0 = create_train_state(cfg, params)
    state = table.place_state(state0)
    step = pjit_in_graph_per_super_step(
        cfg, net, table, 2, state_template=state0, layout="dp")

    p_before = np.asarray(ring.take_prios())
    params_before = [np.asarray(x) for x in jax.tree.leaves(state.params)]
    meta = ring.per_meta()
    st, p_after, losses = step(state, ring.snapshot(), ring.take_prios(),
                               meta["seq_meta"], meta["first"],
                               jnp.asarray(3, jnp.uint32))
    losses, p_after = np.asarray(losses), np.asarray(p_after)
    assert np.isfinite(losses).all() and losses.shape == (2,)
    assert (p_after[p_before == 0] == 0).all()
    assert (p_after != p_before).any(), "scatter must write feedback"
    changed = np.flatnonzero(p_after != p_before)
    assert (p_before[changed] > 0).all()
    # params actually moved
    moved = any(
        not np.allclose(a, np.asarray(b))
        for a, b in zip(params_before, jax.tree.leaves(st.params)))
    assert moved


def test_in_graph_per_dp_layout_matches_single_device():
    """The dp-sharded layout is a pure layout choice: over the SAME
    global ring content, the dp=4-sharded run of the (only) entry point
    and a single-device trivial-mesh run draw identical strata and agree
    on losses, scattered priorities, and params at reduction-order
    round-off.  (Block→slab ROUTING does depend on the dp size — rings
    filled under different dp hold the same blocks in permuted global
    slots — so the invariant is content-for-content, not
    fill-for-fill.)"""
    cfg = make_cfg(superstep_k=2, mesh_shape=(("dp", 4), ("tp", 2)),
                   device_ring_layout="dp")
    mesh, buf, ring = dp_filled(cfg, n_blocks=6)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    meta = ring.per_meta()
    p_start = np.asarray(ring.take_prios())
    snap_host = jax.device_get(ring.snapshot())
    seq_meta = np.asarray(meta["seq_meta"])
    first = np.asarray(meta["first"])
    idx5 = jnp.asarray(5, jnp.uint32)

    s0 = create_train_state(cfg, params)
    s1, p1, l1 = ig_step(cfg, net, 2, s0)(
        s0, snap_host, jnp.asarray(p_start), seq_meta, first, idx5)

    table = ShardingTable(mesh, cfg)
    sN0 = create_train_state(cfg, params)
    stepN = pjit_in_graph_per_super_step(
        cfg, net, table, 2, state_template=sN0, layout="dp")
    sN, pN, lN = stepN(
        table.place_state(sN0), ring.snapshot(), ring.take_prios(),
        meta["seq_meta"], meta["first"], idx5)

    np.testing.assert_allclose(np.asarray(l1), np.asarray(lN), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                               rtol=1e-4, atol=1e-7)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_train_end_to_end_in_graph_per_dp_layout():
    """Full threaded fabric: device PER over a dp-sharded ring on a
    dp=4 x tp=2 mesh — the capacity-scaling composition (pod-size
    replay + zero-host-round-trip priorities) the round-4 guard
    forbade."""
    from r2d2_tpu.train import train

    cfg = make_cfg(game_name="Fake", superstep_k=2, training_steps=8,
                   device_ring_layout="dp", log_interval=0.2,
                   mesh_shape=(("dp", 4), ("tp", 2)))
    metrics = train(
        cfg,
        env_factory=lambda c, seed: FakeAtariEnv(
            obs_shape=c.stored_obs_shape, action_dim=A, seed=seed),
        use_mesh=True, verbose=False)
    assert metrics["num_updates"] >= cfg.training_steps
    assert np.isfinite(metrics["mean_loss"])
    assert not metrics["fabric_failed"]


def test_in_graph_per_without_ring_fails_fast():
    """in_graph_per on the ring-less host fallback must fail at buffer
    construction with the remedy — not as an AttributeError in an actor
    thread at the first block commit."""
    cfg = make_cfg()
    with pytest.raises(ValueError, match="in_graph_per=False"):
        ReplayBuffer(cfg, A, rng=np.random.default_rng(0),
                     device_ring=None)


def test_train_degrades_in_graph_per_without_ring(monkeypatch):
    """The flagship presets default in_graph_per=True; on a host whose
    device budget rejects the ring, train() must warn and continue on
    host-sampled PER (the reference's behavior is host replay, never a
    crash).  Forced here by making every ring look too big.

    Regression (ADVICE r5 high): _build used to flip in_graph_per only on
    its LOCAL cfg, so train() still stripped the priority thread while
    the learner took the host-sampled path — after ~8 updates (the
    priority queue depth) the undrained queue wedged the learner forever.
    training_steps=16 runs past that depth plus the superstep pipeline,
    and the host tree must carry real priority mass with the feedback
    counter fully applied, so the wedge can never regress silently."""
    import importlib
    import warnings

    train_mod = importlib.import_module("r2d2_tpu.train")

    built = {}

    class SpyBuffer(ReplayBuffer):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            built["buffer"] = self

    monkeypatch.setattr(train_mod, "_device_memory_bytes", lambda: 1)
    monkeypatch.setattr(train_mod, "ReplayBuffer", SpyBuffer)
    cfg = make_cfg(game_name="Fake", superstep_k=2, training_steps=16,
                   log_interval=0.2)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        metrics = train_mod.train(
            cfg,
            env_factory=lambda c, seed: FakeAtariEnv(
                obs_shape=c.stored_obs_shape, action_dim=A, seed=seed),
            verbose=False)
    assert any("in_graph_per disabled" in str(x.message) for x in w)
    assert metrics["num_updates"] >= cfg.training_steps
    assert np.isfinite(metrics["mean_loss"])
    assert not metrics["fabric_failed"]
    # the degraded run's PER plane is the HOST tree: actor-side priorities
    # landed in it (mass > 0 — in_graph mode keeps it exactly empty), and
    # every learner update's feedback came back through the priority
    # thread (the path the stripped-thread wedge starved)
    buf = built["buffer"]
    assert buf.tree.total > 0.0
    assert metrics["buffer_training_steps"] == metrics["num_updates"] >= 16


def test_train_sync_accepts_in_graph_preset():
    """train_sync force-disables device_replay; it must drop in_graph_per
    with it (the pair is validated together) so the deterministic
    debug trainer accepts the flagship presets unchanged."""
    from r2d2_tpu.train import train_sync

    cfg = make_cfg(game_name="Fake", training_steps=3)
    out = train_sync(cfg, env_factory=lambda c, seed: FakeAtariEnv(
        obs_shape=c.stored_obs_shape, action_dim=A, seed=seed))
    assert out["num_updates"] >= 3
    assert np.isfinite(out["mean_loss"])


@pytest.mark.slow
def test_train_end_to_end_in_graph_per_dp_fused():
    """The full composition stack at once: dp-sharded ring + device PER
    + fused double unroll on a dp=4 x tp=2 mesh — every r4/r5 throughput
    feature live in one fabric."""
    from r2d2_tpu.train import train

    cfg = make_cfg(game_name="Fake", superstep_k=2, training_steps=8,
                   device_ring_layout="dp", fused_double_unroll=True,
                   log_interval=0.2, mesh_shape=(("dp", 4), ("tp", 2)))
    metrics = train(
        cfg,
        env_factory=lambda c, seed: FakeAtariEnv(
            obs_shape=c.stored_obs_shape, action_dim=A, seed=seed),
        use_mesh=True, verbose=False)
    assert metrics["num_updates"] >= cfg.training_steps
    assert np.isfinite(metrics["mean_loss"])
    assert not metrics["fabric_failed"]
