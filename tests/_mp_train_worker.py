"""Worker for tests/test_multiprocess.py::test_two_process_full_train.

Runs the FULL threaded trainer (actor fleet + replay + learner + logging
under the Supervisor) with multi-host device replay in a 2-process JAX
runtime.  This is the integration surface the learner-direct worker
(_mp_worker.py) cannot cover: the actor thread consumes published params
concurrently with the learner's collectives, which deadlocks the pod if
any published leaf is a global-mesh array (regression: Learner._publish
must hand actors process-local arrays).

Usage: python _mp_train_worker.py <port> <process_id> <out_json>
           [device_replay] [in_graph_per]

``device_replay`` (default "1"): "0" runs the host-staged multi-host data
plane (Learner.run with host_local_batch) instead — the same actor/publish
concurrency, different learner loop.

``in_graph_per`` (default "0"): "1" runs the device-resident PER
drivetrain over the per-host dp slabs (Learner._run_device_in_graph_per
multi-host: stitched global PER views, lockstep SPMD dispatches, zero
host priority traffic).
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

PORT, PID, OUT = sys.argv[1], int(sys.argv[2]), sys.argv[3]
DEVICE_REPLAY = (sys.argv[4] if len(sys.argv) > 4 else "1") == "1"
IN_GRAPH_PER = (sys.argv[5] if len(sys.argv) > 5 else "0") == "1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import faulthandler  # noqa: E402

# a deadlock shows its stacks instead of a silent parent-side timeout
faulthandler.dump_traceback_later(420, exit=True)

from r2d2_tpu.parallel.distributed import init_distributed  # noqa: E402

init_distributed(coordinator_address=f"localhost:{PORT}", num_processes=2,
                 process_id=PID)

import numpy as np  # noqa: E402

from r2d2_tpu.config import test_config  # noqa: E402
from r2d2_tpu.envs.fake import FakeAtariEnv  # noqa: E402
from r2d2_tpu.train import train  # noqa: E402

cfg = test_config(game_name="Fake", device_replay=DEVICE_REPLAY,
                  in_graph_per=IN_GRAPH_PER,
                  superstep_k=2,
                  superstep_pipeline=2,  # multihost pipelined harvest +
                                         # exit drain must stay deadlock-free
                  training_steps=8, log_interval=0.3, num_actors=2,
                  weight_publish_interval=2,  # force publishes mid-run
                  mesh_shape=(("dp", 4), ("tp", 2)))
m = train(cfg, env_factory=lambda c, s: FakeAtariEnv(
              obs_shape=c.stored_obs_shape, action_dim=4, seed=s + 31 * PID),
          use_mesh=True, verbose=False)

results = dict(
    num_updates=int(m["num_updates"]),
    mean_loss=float(m["mean_loss"]),
    env_steps=int(m["env_steps"]),
    fabric_failed=bool(m["fabric_failed"]),
    loss_finite=bool(np.isfinite(m["mean_loss"])),
)
with open(OUT, "w") as f:
    json.dump(results, f)
print("train worker", PID, "done")
