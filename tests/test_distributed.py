"""Multi-host helpers (parallel/distributed.py), exercised single-process
on the 8-device CPU mesh — the degenerate case the helpers promise to
handle identically."""
import jax
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.parallel.distributed import (
    dp_rows_for_process,
    host_batch_size,
    host_local_batch,
    init_distributed,
    local_rows,
    sync_counter,
)
from r2d2_tpu.parallel.mesh import make_mesh
from r2d2_tpu.parallel.sharding import (
    DEVICE_BATCH_KEYS,
    ShardingTable,
    shard_batch,
)
from r2d2_tpu.utils.batch import synthetic_batch


@pytest.fixture(scope="module")
def mesh():
    cfg = make_test_config(mesh_shape=(("dp", 4),))
    return make_mesh(cfg)


def test_init_distributed_single_process():
    info = init_distributed()  # no coordinator configured → no-op
    assert info == {"process_id": 0, "process_count": 1}


def test_dp_rows_single_process_owns_everything(mesh):
    assert dp_rows_for_process(mesh, 8) == slice(0, 8)


def test_host_local_batch_matches_device_put(mesh):
    cfg = make_test_config(mesh_shape=(("dp", 4),))
    rng = np.random.default_rng(0)
    batch = synthetic_batch(cfg, 4, rng)
    local = {k: batch[k] for k in DEVICE_BATCH_KEYS}

    global_arrays = host_local_batch(mesh, local)
    reference = shard_batch(ShardingTable(mesh, cfg), batch)
    for k in DEVICE_BATCH_KEYS:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(global_arrays[k])),
            np.asarray(jax.device_get(reference[k])), err_msg=k)
        assert global_arrays[k].sharding == reference[k].sharding, k


def test_host_local_batch_feeds_sharded_step(mesh):
    """The assembled global batch must be consumable by the real sharded
    train step (end-to-end device-batch path of a multi-host learner)."""
    from r2d2_tpu.learner.step import create_train_state
    from r2d2_tpu.models.network import create_network, init_params
    from r2d2_tpu.parallel.sharding import pjit_train_step

    cfg = make_test_config(mesh_shape=(("dp", 4),), batch_size=8)
    net = create_network(cfg, 4)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    table = ShardingTable(mesh, cfg)
    state0 = create_train_state(cfg, params)
    state = table.place_state(state0)
    step = pjit_train_step(cfg, net, table, state_template=state0)

    rng = np.random.default_rng(1)
    batch = synthetic_batch(cfg, 4, rng)
    dev = host_local_batch(mesh, {k: batch[k] for k in DEVICE_BATCH_KEYS})
    state, loss, priorities = step(state, dev)
    assert np.isfinite(float(jax.device_get(loss)))
    assert np.asarray(jax.device_get(priorities)).shape == (8,)


def test_sync_counter_identity_single_process():
    assert sync_counter(1234) == 1234
    assert sync_counter(7, reduce="sum") == 7


def test_host_batch_size_single_process_is_global(mesh):
    cfg = make_test_config(mesh_shape=(("dp", 4),), batch_size=8)
    assert host_batch_size(cfg, mesh) == 8


def test_local_rows_roundtrip_dp_sharded(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    arr = jax.device_put(x, NamedSharding(mesh, P("dp")))
    np.testing.assert_array_equal(local_rows(arr), x)


def test_local_rows_dedups_replicated_axis():
    """With a 2-D (dp, tp) mesh, each dp row-shard is replicated across tp
    devices; local_rows must return each row range exactly once."""
    cfg = make_test_config(mesh_shape=(("dp", 2), ("tp", 2)))
    m = make_mesh(cfg)
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(4 * 2, dtype=np.float32).reshape(4, 2)
    arr = jax.device_put(x, NamedSharding(m, P("dp")))
    np.testing.assert_array_equal(local_rows(arr), x)


def test_dp_rows_with_trailing_dp_axis():
    """dp need not be the leading mesh axis of the CONFIG spec (the
    canonical mesh still orders axes dp, fsdp, tp)."""
    cfg = make_test_config(mesh_shape=(("tp", 2), ("dp", 2)))
    m = make_mesh(cfg)
    assert dp_rows_for_process(m, 8) == slice(0, 8)
