"""Multi-device learner tests on the virtual 8-device CPU mesh.

The conftest forces ``--xla_force_host_platform_device_count=8``, so the
GSPMD-sharded train step executes real collectives here (SURVEY.md §4).
"""
import jax
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.learner.step import create_train_state, jit_train_step
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.parallel.mesh import (
    make_mesh,
    replicate_state,
    shard_batch,
    sharded_train_step,
)

A = 4


def make_batch(cfg, rng):
    B, T, L = cfg.batch_size, cfg.seq_len, cfg.learning_steps
    return dict(
        obs=rng.integers(0, 255, (B, T, *cfg.obs_shape), dtype=np.uint8),
        last_action=rng.random((B, T, A)).astype(np.float32),
        last_reward=rng.random((B, T)).astype(np.float32),
        hidden=rng.normal(size=(B, 2, cfg.lstm_layers, cfg.hidden_dim)
                          ).astype(np.float32),
        action=rng.integers(0, A, (B, L)).astype(np.int32),
        n_step_reward=rng.random((B, L)).astype(np.float32),
        n_step_gamma=np.full((B, L), 0.99, np.float32),
        burn_in=np.full(B, cfg.burn_in_steps, np.int32),
        learning=np.full(B, L, np.int32),
        forward=np.full(B, cfg.forward_steps, np.int32),
        is_weights=np.ones(B, np.float32),
    )


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_make_mesh_default_spans_all_devices():
    cfg = make_test_config()
    mesh = make_mesh(cfg)
    assert mesh.shape == {"dp": 8}


def test_make_mesh_custom_shape_and_errors():
    cfg = make_test_config(mesh_shape=(("dp", 4),))
    assert make_mesh(cfg).shape == {"dp": 4}
    with pytest.raises(ValueError, match="devices"):
        make_mesh(make_test_config(mesh_shape=(("dp", 16),)))
    with pytest.raises(ValueError, match="divisible"):
        net = create_network(make_test_config(batch_size=6), A)
        sharded_train_step(make_test_config(batch_size=6), net,
                           make_mesh(make_test_config()))


@pytest.mark.slow
def test_sharded_step_matches_single_device():
    """dp=8 GSPMD step must reproduce the single-device step: same loss,
    priorities, and updated params (the semantics-preservation contract of
    SURVEY.md §7: per-device batch 64/n with global reductions)."""
    cfg = make_test_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    batch = make_batch(cfg, np.random.default_rng(0))

    step1 = jit_train_step(cfg, net)
    s1, loss1, prio1 = step1(create_train_state(cfg, params),
                             jax.tree.map(jax.numpy.asarray, batch))

    mesh = make_mesh(cfg)
    stepN = sharded_train_step(cfg, net, mesh)
    sN, lossN, prioN = stepN(replicate_state(mesh, create_train_state(cfg, params)),
                             shard_batch(mesh, batch))

    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_fused_double_unroll_sharded_matches_single_device():
    """The fused online+target unroll (vmap over stacked params) must
    survive GSPMD partitioning: dp=8 fused step == single-device fused
    step == single-device unfused step."""
    cfg = make_test_config(fused_double_unroll=True)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    batch = make_batch(cfg, np.random.default_rng(3))

    s1, loss1, prio1 = jit_train_step(cfg, net)(
        create_train_state(cfg, params),
        jax.tree.map(jax.numpy.asarray, batch))
    s0, loss0, _ = jit_train_step(cfg.replace(fused_double_unroll=False),
                                  net)(create_train_state(cfg, params),
                                       jax.tree.map(jax.numpy.asarray,
                                                    batch))
    assert float(loss0) == pytest.approx(float(loss1), rel=1e-5)

    mesh = make_mesh(cfg)
    sN, lossN, prioN = sharded_train_step(cfg, net, mesh)(
        replicate_state(mesh, create_train_state(cfg, params)),
        shard_batch(mesh, batch))
    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params),
                      jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_sharded_multistep_stays_in_sync():
    """Run 3 sharded steps (with in-graph target sync crossing its cadence)
    and compare against 3 single-device steps."""
    cfg = make_test_config(target_net_update_interval=2)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    batches = [make_batch(cfg, rng) for _ in range(3)]

    step1 = jit_train_step(cfg, net)
    s1 = create_train_state(cfg, params)
    for b in batches:
        s1, loss1, _ = step1(s1, jax.tree.map(jax.numpy.asarray, b))

    mesh = make_mesh(cfg)
    stepN = sharded_train_step(cfg, net, mesh)
    sN = replicate_state(mesh, create_train_state(cfg, params))
    for b in batches:
        sN, lossN, _ = stepN(sN, shard_batch(mesh, b))

    assert int(s1.step) == int(sN.step) == 3
    for p1, pN in zip(jax.tree.leaves(s1.target_params),
                      jax.tree.leaves(sN.target_params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_mp_sharded_step_matches_single_device():
    """2-D (dp=4, mp=2) mesh: kernels shard over mp, batch over dp; the
    result must still match the single-device step exactly."""
    from r2d2_tpu.parallel.mesh import state_shardings
    from jax.sharding import PartitionSpec as P

    cfg = make_test_config(mesh_shape=(("dp", 4), ("mp", 2)))
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(2))
    batch = make_batch(cfg, np.random.default_rng(2))

    step1 = jit_train_step(cfg, net)
    s1, loss1, prio1 = step1(create_train_state(cfg, params),
                             jax.tree.map(jax.numpy.asarray, batch))

    mesh = make_mesh(cfg)
    assert mesh.shape == {"dp": 4, "mp": 2}
    state0 = create_train_state(cfg, params)
    stepN = sharded_train_step(cfg, net, mesh, state_template=state0)
    sN0 = replicate_state(mesh, state0)

    # the big kernels must actually be mp-sharded (not silently replicated)
    shards = state_shardings(mesh, state0)
    wi_spec = shards.params["params"]["lstm_0"]["wi"].spec
    assert wi_spec == P(None, "mp")
    # and the adam moments mirror the param layout
    mu = shards.opt_state[1][0].mu["params"]["lstm_0"]["wi"].spec
    assert mu == P(None, "mp")

    sN, lossN, prioN = stepN(sN0, shard_batch(mesh, batch))
    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


def test_mp_mesh_requires_state_template():
    cfg = make_test_config(mesh_shape=(("dp", 4), ("mp", 2)))
    net = create_network(cfg, A)
    with pytest.raises(ValueError, match="state_template"):
        sharded_train_step(cfg, net, make_mesh(cfg))


