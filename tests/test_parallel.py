"""Multi-device learner tests on the virtual 8-device CPU mesh.

The conftest forces ``--xla_force_host_platform_device_count=8``, so the
table-driven pjit train step (parallel/sharding.py) executes real
collectives here (SURVEY.md §4).  Layout parity holds at reduction-order
round-off: partitioning a batch reassociates the gradient sums (partial
dots + psum vs one full dot), so params match to f32 ulps, not bits —
bit-exactness across runs of the SAME layout is pinned in
tests/test_sharding.py.
"""
import jax
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.learner.step import create_train_state
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.parallel.mesh import make_mesh, trivial_mesh
from r2d2_tpu.parallel.sharding import (
    ShardingTable,
    pjit_train_step,
    shard_batch,
)

A = 4


def make_batch(cfg, rng):
    B, T, L = cfg.batch_size, cfg.seq_len, cfg.learning_steps
    return dict(
        obs=rng.integers(0, 255, (B, T, *cfg.obs_shape), dtype=np.uint8),
        last_action=rng.random((B, T, A)).astype(np.float32),
        last_reward=rng.random((B, T)).astype(np.float32),
        hidden=rng.normal(size=(B, 2, cfg.lstm_layers, cfg.hidden_dim)
                          ).astype(np.float32),
        action=rng.integers(0, A, (B, L)).astype(np.int32),
        n_step_reward=rng.random((B, L)).astype(np.float32),
        n_step_gamma=np.full((B, L), 0.99, np.float32),
        burn_in=np.full(B, cfg.burn_in_steps, np.int32),
        learning=np.full(B, L, np.int32),
        forward=np.full(B, cfg.forward_steps, np.int32),
        is_weights=np.ones(B, np.float32),
    )


def single_device_step(cfg, net, params):
    """The SAME entry point on a trivial 1-device mesh — the unified
    step's degenerate case, used as the semantics oracle."""
    state = create_train_state(cfg, params)
    table = ShardingTable(trivial_mesh(), cfg)
    return pjit_train_step(cfg, net, table, state_template=state), \
        table.place_state(state)


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_make_mesh_default_spans_all_devices():
    cfg = make_test_config()
    mesh = make_mesh(cfg)
    assert mesh.shape == {"dp": 8, "fsdp": 1, "tp": 1}


def test_make_mesh_custom_shape_and_errors():
    cfg = make_test_config(mesh_shape=(("dp", 4),))
    assert make_mesh(cfg).shape == {"dp": 4, "fsdp": 1, "tp": 1}
    with pytest.raises(ValueError, match="devices"):
        make_mesh(make_test_config(mesh_shape=(("dp", 16),)))
    with pytest.raises(ValueError, match="divisible"):
        cfg6 = make_test_config(batch_size=6)
        net = create_network(cfg6, A)
        state = create_train_state(cfg6, init_params(
            cfg6, net, jax.random.PRNGKey(0)))
        pjit_train_step(cfg6, net, ShardingTable(
            make_mesh(make_test_config()), cfg6), state_template=state)


def test_mp_axis_rejected():
    """The r8-era 'mp' axis is gone; config validation names the fold."""
    with pytest.raises(ValueError, match="folded into 'tp'"):
        make_test_config(mesh_shape=(("dp", 4), ("mp", 2)))


@pytest.mark.slow
def test_sharded_step_matches_single_device():
    """dp=8 GSPMD step must reproduce the single-device step: same loss,
    priorities, and updated params (the semantics-preservation contract of
    SURVEY.md §7: per-device batch 64/n with global reductions)."""
    cfg = make_test_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    batch = make_batch(cfg, np.random.default_rng(0))

    step1, s0 = single_device_step(cfg, net, params)
    s1, loss1, prio1 = step1(s0, dict(batch))

    mesh = make_mesh(cfg)
    table = ShardingTable(mesh, cfg)
    stateN = create_train_state(cfg, params)
    stepN = pjit_train_step(cfg, net, table, state_template=stateN)
    sN, lossN, prioN = stepN(table.place_state(stateN),
                             shard_batch(table, batch))

    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_fused_double_unroll_sharded_matches_single_device():
    """The fused online+target unroll (vmap over stacked params) must
    survive GSPMD partitioning: dp=8 fused step == single-device fused
    step == single-device unfused step."""
    cfg = make_test_config(fused_double_unroll=True)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    batch = make_batch(cfg, np.random.default_rng(3))

    step1, s10 = single_device_step(cfg, net, params)
    s1, loss1, prio1 = step1(s10, dict(batch))
    step0, s00 = single_device_step(
        cfg.replace(fused_double_unroll=False), net, params)
    s0, loss0, _ = step0(s00, dict(batch))
    assert float(loss0) == pytest.approx(float(loss1), rel=1e-5)

    mesh = make_mesh(cfg)
    table = ShardingTable(mesh, cfg)
    stateN = create_train_state(cfg, params)
    sN, lossN, prioN = pjit_train_step(
        cfg, net, table, state_template=stateN)(
        table.place_state(stateN), shard_batch(table, batch))
    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params),
                      jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_sharded_multistep_stays_in_sync():
    """Run 3 sharded steps (with in-graph target sync crossing its cadence)
    and compare against 3 single-device steps."""
    cfg = make_test_config(target_net_update_interval=2)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    batches = [make_batch(cfg, rng) for _ in range(3)]

    step1, s1 = single_device_step(cfg, net, params)
    for b in batches:
        s1, loss1, _ = step1(s1, dict(b))

    mesh = make_mesh(cfg)
    table = ShardingTable(mesh, cfg)
    stateN = create_train_state(cfg, params)
    stepN = pjit_train_step(cfg, net, table, state_template=stateN)
    sN = table.place_state(stateN)
    for b in batches:
        sN, lossN, _ = stepN(sN, shard_batch(table, b))

    assert int(jax.device_get(s1.step)) == int(jax.device_get(sN.step)) == 3
    for p1, pN in zip(jax.tree.leaves(s1.target_params),
                      jax.tree.leaves(sN.target_params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_tp_sharded_step_matches_single_device():
    """(dp=4, tp=2) mesh: the table column-splits the LSTM/Dense kernels
    over tp and the batch shards over dp; the result must still match the
    single-device step at reduction round-off."""
    from jax.sharding import PartitionSpec as P

    cfg = make_test_config(mesh_shape=(("dp", 4), ("tp", 2)))
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(2))
    batch = make_batch(cfg, np.random.default_rng(2))

    step1, s10 = single_device_step(cfg, net, params)
    s1, loss1, prio1 = step1(s10, dict(batch))

    mesh = make_mesh(cfg)
    assert mesh.shape == {"dp": 4, "fsdp": 1, "tp": 2}
    table = ShardingTable(mesh, cfg)
    state0 = create_train_state(cfg, params)
    stepN = pjit_train_step(cfg, net, table, state_template=state0)
    sN0 = table.place_state(state0)

    # the big kernels must actually be tp-sharded (not silently replicated)
    shards = table.state_shardings(state0)
    wi_spec = shards.params["params"]["lstm_0"]["wi"].spec
    assert wi_spec[-1] == "tp"
    # and the adam moments mirror the param layout
    mu = shards.opt_state[1][0].mu["params"]["lstm_0"]["wi"].spec
    assert mu == wi_spec

    sN, lossN, prioN = stepN(sN0, shard_batch(table, batch))
    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    np.testing.assert_allclose(np.asarray(prio1), np.asarray(prioN),
                               rtol=1e-4, atol=1e-6)
    for p1, pN in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_fsdp_sharded_step_matches_single_device():
    """(dp=2, fsdp=2) mesh: params AND adam moments shard a large dim over
    fsdp — XLA inserts the allgather/reduce-scatter pairs — and training
    still matches the single-device trajectory."""
    cfg = make_test_config(mesh_shape=(("dp", 2), ("fsdp", 2)))
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(5))
    batch = make_batch(cfg, np.random.default_rng(5))

    step1, s10 = single_device_step(cfg, net, params)
    s1, loss1, _ = step1(s10, dict(batch))

    table = ShardingTable(make_mesh(cfg), cfg)
    state0 = create_train_state(cfg, params)
    # at least one kernel must genuinely shard over fsdp
    shards = table.state_shardings(state0)
    specs = [s.spec for s in jax.tree.leaves(shards)]
    assert any("fsdp" in [ax for ax in sp if ax is not None]
               for sp in specs if sp), specs
    sN, lossN, _ = pjit_train_step(cfg, net, table, state_template=state0)(
        table.place_state(state0), shard_batch(table, batch))
    assert float(loss1) == pytest.approx(float(lossN), rel=1e-5)
    for p1, pN in zip(jax.tree.leaves(s1.params), jax.tree.leaves(sN.params)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(pN),
                                   rtol=1e-4, atol=1e-6)


def test_pjit_step_requires_state_template():
    cfg = make_test_config(mesh_shape=(("dp", 4), ("tp", 2)))
    net = create_network(cfg, A)
    with pytest.raises(ValueError, match="state_template"):
        pjit_train_step(cfg, net, ShardingTable(make_mesh(cfg), cfg))
