"""Degraded-mode resilience layer (ISSUE 7): Deadline / RetryPolicy /
CircuitBreaker primitives, serve-plane failover (fleet-local fallback
inference, half-open re-attach with hidden resync — bit-exact across the
whole failover cycle), service-side hardening (partial batches, stale
request drops, dropped/garbled response recovery), the param-staleness
watchdog, the anakin wedge_dispatch snapshot-then-abort drill, and the
three-state /healthz contract.
"""
import multiprocessing as mp
import threading
import time

import jax
import numpy as np
import pytest

from r2d2_tpu.actor import VectorActor, make_act_fn
from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.envs.fake import FakeAtariEnv
from r2d2_tpu.models.network import create_network, init_params
from r2d2_tpu.parallel.actor_procs import ProcessFleetPlane
from r2d2_tpu.parallel.inference_service import RemoteActClient
from r2d2_tpu.utils.chaos import ChaosInjector
from r2d2_tpu.utils.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    Deadline,
    RetryPolicy,
)
from r2d2_tpu.utils.store import ParamStore

A = 4


def make_fake_env(cfg, seed):
    """Module-level (picklable) factory for the spawn children."""
    return FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=A,
                        seed=seed, episode_len=32)


def _serve_cfg(**kw):
    base = dict(num_actors=2, actor_transport="process",
                actor_inference="serve")
    base.update(kw)
    return make_test_config(**base)


def _long_episode_envs(cfg, n):
    return [FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=A,
                         seed=i, episode_len=500) for i in range(n)]


# ---------------------------------------------------------- primitives

def test_deadline_budget_and_unbounded():
    d = Deadline(0.15)
    assert not d.expired
    assert 0 < d.remaining() <= 0.15
    assert d.poll_timeout(0.2) <= 0.15 + 1e-6
    time.sleep(0.2)
    assert d.expired
    assert d.remaining() == 0.0
    assert d.poll_timeout(0.2) == 0.001     # floored non-busy poll
    # budget <= 0 means unbounded
    u = Deadline(0.0)
    time.sleep(0.01)
    assert not u.expired
    assert u.remaining() == float("inf")
    assert u.remaining(0.2) == 0.2
    assert u.poll_timeout(0.2) == 0.2


def test_retry_policy_bounded_jittered_exponential():
    p = RetryPolicy(attempts=4, base=0.1, max_delay=10.0, jitter=0.2,
                    seed=3)
    delays = [p.backoff(i) for i in range(1, p.attempts)]
    assert len(delays) == 3                 # attempts - 1 sleeps
    for i, d in enumerate(delays):
        nominal = 0.1 * 2 ** i
        assert 0.8 * nominal - 1e-9 <= d <= 1.2 * nominal + 1e-9
    # deterministic given the seed
    p2 = RetryPolicy(attempts=4, base=0.1, max_delay=10.0, jitter=0.2,
                     seed=3)
    assert delays == [p2.backoff(i) for i in range(1, p2.attempts)]
    # cap applies before jitter
    pc = RetryPolicy(attempts=8, base=1.0, max_delay=1.5, jitter=0.0)
    assert max(pc.backoff(i) for i in range(1, pc.attempts)) == 1.5
    with pytest.raises(ValueError, match="attempts"):
        RetryPolicy(attempts=0)


def test_circuit_breaker_state_machine_and_telemetry():
    transitions = []
    b = CircuitBreaker(name="t", failure_threshold=2, cooldown=0.2,
                       on_transition=lambda n, o, s: transitions.append(
                           (n, o, s)))
    assert b.state == CLOSED and b.allow_attempt()
    b.record_failure()
    assert b.state == CLOSED                # below threshold
    b.record_failure()
    assert b.state == OPEN
    assert transitions == [("t", CLOSED, OPEN)]
    assert not b.allow_attempt()            # open: local fallback
    time.sleep(0.25)
    assert b.state == HALF_OPEN             # cooldown elapsed (lazy)
    # the lazy flip still fires on_transition — the circuit_state gauge
    # must be able to show all three documented states
    assert transitions[-1] == ("t", OPEN, HALF_OPEN)
    assert b.allow_attempt()                # THE probe slot
    assert not b.allow_attempt()            # only one probe per window
    b.record_failure()                      # probe failed -> re-open
    assert b.state == OPEN and b.opens == 2
    time.sleep(0.25)
    assert b.allow_attempt()
    b.record_success()                      # probe succeeded -> closed
    assert b.state == CLOSED
    assert transitions == [("t", CLOSED, OPEN),
                           ("t", OPEN, HALF_OPEN),
                           ("t", HALF_OPEN, OPEN),
                           ("t", OPEN, HALF_OPEN),
                           ("t", HALF_OPEN, CLOSED)]
    snap = b.snapshot()
    assert snap["opens"] == 2 and snap["probes"] == 2
    assert snap["state_name"] == "closed"
    # consecutive-failure count resets on success
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == CLOSED


def test_config_act_response_timeout_and_dispatch_deadline_validation():
    with pytest.raises(ValueError, match="act_response_timeout"):
        make_test_config(act_response_timeout=0.0)
    with pytest.raises(ValueError, match="act_response_timeout"):
        make_test_config(act_response_timeout=-1.0)
    with pytest.raises(ValueError, match="dispatch_deadline"):
        make_test_config(dispatch_deadline=-0.1)
    assert make_test_config(act_response_timeout=2.5).act_response_timeout \
        == 2.5
    assert make_test_config(dispatch_deadline=0.0).dispatch_deadline == 0.0


def test_cli_act_response_timeout_flag():
    from r2d2_tpu import cli as cli_mod

    # an invalid value must fail loudly at the parser (Config validation)
    with pytest.raises(SystemExit):
        cli_mod.main(["train", "--preset", "test", "--game", "Fake",
                      "--act-response-timeout", "0", "--sync"])

    # --set override path resolves the field (config-integrity liveness)
    class Args:
        preset = "test"
        game = None
        actors = None
        seed = None
        training_steps = None
        overrides = [("act_response_timeout", 3.5)]
        actor_transport = None
        actor_inference = None

    assert cli_mod.build_config(Args()).act_response_timeout == 3.5


# ------------------------------------------- new chaos kinds parse/fire

def test_chaos_new_kinds_parse_and_helpers():
    from r2d2_tpu.utils.chaos import parse_spec

    spec = parse_spec("freeze_service:at=2,dur=4;stall_pump:at=1,dur=3;"
                      "drop_act_response:every=2;"
                      "garble_act_response:at=1;wedge_dispatch:at=1,dur=9")
    assert spec["freeze_service"] == {"at": 2.0, "dur": 4.0}
    # config validation accepts the new kinds
    assert make_test_config(
        chaos_spec="freeze_service:at=1,dur=2").chaos_spec

    inj = ChaosInjector("freeze_service:at=2,dur=4;stall_pump:at=1,dur=3;"
                        "drop_act_response:every=2;"
                        "garble_act_response:at=1;"
                        "wedge_dispatch:at=1,dur=9", seed=0)
    assert inj.service_freeze_seconds() == 0.0     # opportunity 1
    assert inj.service_freeze_seconds() == 4.0     # at=2 fires once
    assert inj.service_freeze_seconds() == 0.0
    assert inj.pump_stall_seconds() == 3.0
    assert inj.pump_stall_seconds() == 0.0
    assert [inj.drop_response() for _ in range(4)] == [False, True,
                                                      False, True]
    assert inj.garble_response() is True
    assert inj.garble_response() is False
    assert inj.dispatch_wedge_seconds() == 9.0
    assert inj.counts()["wedge_dispatch"] == 1


# ------------------------------------------- serve-plane failover cycle

def _pump_while(svc, fn):
    """Run ``fn`` (an actor burst) in a thread while pumping the service
    from this one — the in-process stand-in for the fabric's
    ``inference_serve`` loop."""
    done = threading.Event()
    err = []

    def run():
        try:
            fn()
        except BaseException as e:
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 240
    while not done.is_set() and time.time() < deadline:
        svc.serve_once(idle_sleep=0.0)
    t.join(10)
    assert done.is_set(), "actor burst never finished (wedged?)"
    if err:
        raise err[0]


@pytest.mark.timeout(600)
def test_failover_cycle_blocks_bit_exact_and_reattach():
    """THE failover acceptance invariant, as a deterministic three-phase
    drill: (A) attached — normal serve-mode acting; (B) frozen — the
    service stops serving entirely, the fleet's circuit opens after
    bounded retries and acting degrades to the local twin on the pumped
    params; (C) thawed — the half-open probe re-attaches with a hidden
    resync.  The ENTIRE block stream (before, during, and after the
    failover) must be bit-exact vs a pure local-inference run, and the
    server's hidden must re-converge to the fleet's authoritative
    carry."""
    cfg = _serve_cfg(max_episode_steps=20,      # caps fire: peeks covered
                     act_response_timeout=0.3)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))

    got_local, got_serve = [], []
    plane = ProcessFleetPlane(cfg, A, make_fake_env, [0.4, 0.3])
    svc = plane.service
    svc.start(ParamStore(params))
    ch = svc.make_channel(0)
    # the degraded-mode kit a real fleet subprocess gets: the same param
    # snapshot in a local store + the local act twin factory
    client = RemoteActClient(
        cfg, A, 2, ch.producer_info(), mp.get_context("spawn").Event(),
        param_store=ParamStore(jax.device_get(params)),
        local_act_factory=lambda: make_act_fn(cfg, net))
    a2 = VectorActor(cfg, _long_episode_envs(cfg, 2), [0.4, 0.3], client,
                     ParamStore(),
                     sink=lambda b, p, e: got_serve.append((b, p.copy(), e)),
                     rng=np.random.default_rng(5))
    try:
        # warm the service's act compile through a no-state-advance peek
        # so phase A's tight response deadline never races XLA compile
        zero = (np.zeros((2, *cfg.stored_obs_shape), np.uint8),
                np.zeros((2, A), np.float32), np.zeros(2, np.float32),
                np.zeros((2, 2, cfg.lstm_layers, cfg.hidden_dim),
                         np.float32))
        _pump_while(svc, lambda: client.peek(None, *zero))

        # phase A — attached.  Under full-suite load a single act RPC
        # can legitimately exceed its 0.3 s deadline against a LIVE
        # service — the circuit opening on that is the degraded-mode
        # design working, not a test failure.  Poll-with-deadline (the
        # r07 conversion): run small bursts until one completes fully
        # attached (closed breaker, zero local acts in the burst), with
        # a hard deadline instead of asserting the first 20 steps never
        # saw a timeout.
        steps_a = 0
        deadline = time.time() + 180
        while True:
            la0 = client.stats["local_acts"]
            _pump_while(svc, lambda: a2.run(max_steps=5))
            steps_a += 5
            if (steps_a >= 20 and client.breaker.state == CLOSED
                    and client.stats["local_acts"] == la0):
                break
            assert time.time() < deadline, \
                "never reached a fully-attached burst (phase A)"

        # phase B — FROZEN service (nobody pumps serve_once): the first
        # act exhausts its bounded retries, the circuit opens, and the
        # remaining steps run on the local twin — no fleet death, no
        # unbounded wait, blocks keep flowing
        la_b0 = client.stats["local_acts"]
        a2.run(max_steps=17)
        # the circuit opened (half-open probes may have failed against
        # the still-frozen service and re-opened it — each counted)
        assert client.stats["circuit_opens"] >= 1
        assert client.breaker.state != CLOSED
        assert client.stats["local_acts"] == la_b0 + 17   # all local
        assert client.stats["act_retries"] >= 1

        # phase C — thaw: once a cooldown elapses the next commit is the
        # half-open probe (resync mode); poll-with-deadline until it
        # lands and a burst runs fully attached again (under load the
        # first probe itself can time out and re-open — each counted)
        steps_c = 0
        deadline = time.time() + 180
        while True:
            la0 = client.stats["local_acts"]
            _pump_while(svc, lambda: a2.run(max_steps=5))
            steps_c += 5
            if (client.breaker.state == CLOSED
                    and client.stats["local_acts"] == la0):
                break
            assert time.time() < deadline, "never re-attached (phase C)"
            time.sleep(0.05)
        assert svc.resyncs >= 1, "re-attach probe never resynced hidden"
        # phase B's abandoned request tokens were dropped as superseded
        # (the fleet only waits on its newest seq), never answered blind
        assert svc.stale_requests >= 1

        # bit-exact across the WHOLE cycle (the ISSUE 7 acceptance
        # gate): replay the SAME number of steps through a pure
        # local-inference actor and compare the full block streams
        total = steps_a + 17 + steps_c
        a1 = VectorActor(
            cfg, _long_episode_envs(cfg, 2), [0.4, 0.3],
            make_act_fn(cfg, net), ParamStore(params),
            sink=lambda b, p, e: got_local.append((b, p.copy(), e)),
            rng=np.random.default_rng(5))
        a1.run(max_steps=total)
        assert len(got_local) == len(got_serve) > 0
        for (b1, p1, e1), (b2, p2, e2) in zip(got_local, got_serve):
            for f in ("obs", "last_action", "last_reward", "action",
                      "n_step_reward", "n_step_gamma", "hidden",
                      "burn_in_steps", "learning_steps", "forward_steps"):
                np.testing.assert_array_equal(getattr(b1, f),
                                              getattr(b2, f), err_msg=f)
            np.testing.assert_array_equal(p1, p2)
            assert e1 == e2
        # post-re-attach server hidden is the fleet's authoritative carry
        np.testing.assert_array_equal(a1.hidden, a2.hidden)
        np.testing.assert_array_equal(svc.hidden, a2.hidden)
    finally:
        client.close()
        svc.close()


@pytest.mark.timeout(600)
def test_drop_and_garble_response_recovered_by_bounded_retry():
    """The drop_act_response / garble_act_response chaos sites: a lost
    response token and a garbled response payload must both be absorbed
    by the client's bounded retry (counted), never wedge the lockstep
    fleet, and leave the block stream bit-exact (retries resync the
    server hidden from the fleet's carry, so a half-served attempt can
    never double-advance state)."""
    cfg = _serve_cfg(act_response_timeout=0.25)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))

    got_local, got_serve = [], []
    a1 = VectorActor(cfg, _long_episode_envs(cfg, 2), [0.4, 0.3],
                     make_act_fn(cfg, net), ParamStore(params),
                     sink=lambda b, p, e: got_local.append((b, p.copy(), e)),
                     rng=np.random.default_rng(5))
    a1.run(max_steps=41)

    plane = ProcessFleetPlane(cfg, A, make_fake_env, [0.4, 0.3])
    svc = plane.service
    svc.start(ParamStore(params))
    svc.chaos = ChaosInjector(
        "drop_act_response:at=7;garble_act_response:at=15", seed=0)
    ch = svc.make_channel(0)
    client = RemoteActClient(
        cfg, A, 2, ch.producer_info(), mp.get_context("spawn").Event(),
        param_store=ParamStore(jax.device_get(params)),
        local_act_factory=lambda: make_act_fn(cfg, net))
    a2 = VectorActor(cfg, _long_episode_envs(cfg, 2), [0.4, 0.3], client,
                     ParamStore(),
                     sink=lambda b, p, e: got_serve.append((b, p.copy(), e)),
                     rng=np.random.default_rng(5))
    try:
        done = threading.Event()
        err = []

        def run():
            try:
                a2.run(max_steps=41)
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=run)
        t.start()
        deadline = time.time() + 240
        while not done.is_set() and time.time() < deadline:
            svc.serve_once(idle_sleep=0.0)
        t.join(10)
        assert done.is_set(), "a dropped/garbled response wedged the fleet"
        if err:
            raise err[0]

        assert svc.dropped_responses == 1
        assert svc.garbled_responses == 1
        assert client.stats["act_retries"] >= 2     # one per injected fault
        assert client.breaker.state == CLOSED       # retries sufficed
        assert client.stats["circuit_opens"] == 0

        assert len(got_local) == len(got_serve) > 0
        for (b1, p1, e1), (b2, p2, e2) in zip(got_local, got_serve):
            for f in ("obs", "action", "n_step_reward", "hidden"):
                np.testing.assert_array_equal(getattr(b1, f),
                                              getattr(b2, f), err_msg=f)
            np.testing.assert_array_equal(p1, p2)
            assert e1 == e2
        np.testing.assert_array_equal(a1.hidden, a2.hidden)
    finally:
        client.close()
        svc.close()


# ------------------------------------------------- service hardening

def test_partial_batch_counted_when_a_fleet_never_posts():
    """One fleet posts, the other never does: after the batch window the
    act must dispatch anyway (masked lanes) and count a partial batch —
    a dead fleet cannot hold the lockstep window hostage."""
    from r2d2_tpu.parallel.inference_service import act_request_crc

    cfg = _serve_cfg(num_actors=4, actor_fleets=2,
                     inference_batch_window=0.05)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    plane = ProcessFleetPlane(cfg, A, make_fake_env,
                              [0.4, 0.3, 0.2, 0.1])
    svc = plane.service
    svc.start(ParamStore(params))
    ch0 = svc.make_channel(0)
    svc.make_channel(1)                     # attached but silent
    try:
        v = ch0.views
        v["obs"][:] = 7
        v["last_action"][:] = 0.0
        v["last_reward"][:] = 0.0
        v["reset_mask"][:] = 0
        v["req_seq"][0] = 1
        v["req_crc"][0] = act_request_crc(v, 1, 1)
        ch0.req_q.put((1, 1))
        t0 = time.monotonic()
        deadline = time.time() + 60
        while svc.batches == 0 and time.time() < deadline:
            svc.serve_once(idle_sleep=0.0)
        assert svc.batches == 1
        assert svc.partial_batches == 1
        assert svc.health()["partial_batches"] == 1
        assert ch0.rsp_q.get(timeout=10) == 1
        # the window bounded the wait (one window, not a hang)
        assert time.monotonic() - t0 < 30
    finally:
        svc.close()


def test_param_staleness_watchdog_degrades_health():
    """A fleet reporting an older param version than the newest published
    one accrues stale_params_s from the version edge; past the budget the
    plane's resilience verdict (and /healthz) degrades — a dead pump can
    no longer mean silent training on frozen weights."""
    from r2d2_tpu.telemetry.slab import StatsSlabWriter

    cfg = _serve_cfg(actor_fleets=2)
    plane = ProcessFleetPlane(cfg, A, make_fake_env, [0.4, 0.3])
    plane.param_store = ParamStore({"w": np.zeros(2)})   # version 1
    plane.stale_params_budget = 0.15

    w0 = StatsSlabWriter(plane.stats_slab.writer_info(0))
    w1 = StatsSlabWriter(plane.stats_slab.writer_info(1))
    try:
        # before a fleet's FIRST stats publication its slot reads
        # param_version=0 — spawn/compile warm-up, not a dead pump; the
        # clock must not arm or every cold start slower than the budget
        # would flip /healthz to "degraded"
        res = plane.resilience_health()
        assert res["stale_params_s"] == [0.0, 0.0]
        assert not res["degraded"]
        w0.publish(dict(env_steps=10, param_version=1, incarnation=0))
        w1.publish(dict(env_steps=10, param_version=1, incarnation=0))
        res = plane.resilience_health()
        assert res["stale_params_s"] == [0.0, 0.0]
        assert not res["degraded"]

        # the learner publishes version 2; fleet 1's pump never delivers
        plane.param_store.publish({"w": np.ones(2)})
        w0.publish(dict(env_steps=20, param_version=2, incarnation=0))
        w1.publish(dict(env_steps=20, param_version=1, incarnation=0))
        res = plane.resilience_health()
        assert res["stale_params_s"][0] == 0.0
        assert res["max_stale_params_s"] >= 0.0
        time.sleep(0.25)                     # cross the budget
        res = plane.resilience_health()
        assert res["stale_params_s"][1] > plane.stale_params_budget
        assert res["degraded"]
        # the learner publishing AGAIN must not reset fleet 1's clock:
        # staleness is pinned to when the fleet first fell behind, not
        # to the store's last version edge
        plane.param_store.publish({"w": np.full(2, 2.0)})   # version 3
        w0.publish(dict(env_steps=25, param_version=3, incarnation=0))
        prev = res["stale_params_s"][1]
        res = plane.resilience_health()
        assert res["stale_params_s"][1] >= prev
        assert res["degraded"]
        # the per-fleet gauge landed in the registry
        assert plane.registry.get_gauge("fleet.stale_params_s",
                                        fleet="1") > 0
        # catching up clears it
        w1.publish(dict(env_steps=30, param_version=2, incarnation=0))
        res = plane.resilience_health()
        assert res["stale_params_s"] == [0.0, 0.0]
        assert not res["degraded"]
    finally:
        w0.close()
        w1.close()
        plane.stats_slab.close()


def test_circuit_state_from_slab_degrades_health():
    """A serve fleet publishing an open circuit through the stats slab
    must flip the plane's resilience verdict to degraded and surface the
    merged resilience counters."""
    from r2d2_tpu.telemetry.slab import StatsSlabWriter

    cfg = _serve_cfg()
    plane = ProcessFleetPlane(cfg, A, make_fake_env, [0.4, 0.3])
    plane.param_store = ParamStore({"w": np.zeros(2)})
    w0 = StatsSlabWriter(plane.stats_slab.writer_info(0))
    try:
        w0.publish(dict(env_steps=5, param_version=1, incarnation=0,
                        act_retries=4, circuit_opens=2, local_acts=123,
                        circuit_state=1.0))
        res = plane.resilience_health()
        assert res["circuit_states"][0] == OPEN
        assert res["circuits_open"] == 1
        assert res["retries"] == 4
        assert res["circuit_opens"] == 2
        assert res["local_acts"] == 123
        assert res["degraded"]
        h = plane.health()
        assert h["resilience"]["degraded"]
    finally:
        w0.close()
        plane.stats_slab.close()


# --------------------------------------------------- chaos e2e (serve)

@pytest.mark.timeout(600)
@pytest.mark.chaos
def test_train_serve_freeze_service_degrades_and_reattaches():
    """ISSUE 7 acceptance e2e: with freeze_service armed, a serve-mode
    train() run survives with ZERO fleet deaths — the fleets open their
    circuits and keep producing blocks through degraded local inference
    (updates keep flowing), then re-attach after the thaw (circuits
    closed, hidden resynced through probe requests).  The run is stopped
    by SIGTERM once the full freeze→degrade→re-attach cycle has been
    observed in the health stream."""
    import os
    import signal

    from r2d2_tpu.train import train

    cfg = make_test_config(
        game_name="Fake", num_actors=2, actor_fleets=2,
        actor_transport="process", actor_inference="serve",
        training_steps=10 ** 9, log_interval=0.2,
        act_response_timeout=0.5,
        # the site counts one opportunity per SERVED batch, so at=50
        # lands the freeze under real lockstep traffic (past the replay
        # warm-up); dur outlasts the retries+probe window by enough that
        # the 0.2s health stream samples the degraded window even when a
        # loaded CI host starves the log loop for seconds
        chaos_spec="freeze_service:at=50,dur=10")
    seen = dict(degraded_entries=0, degraded_first_steps=None,
                degraded_last_steps=0, cycle_done=False)

    def sink(entry):
        fleet = entry.get("fleet") or {}
        res = fleet.get("resilience") or {}
        if res.get("circuits_open", 0) > 0:
            seen["degraded_entries"] += 1
            if seen["degraded_first_steps"] is None:
                seen["degraded_first_steps"] = entry["training_steps"]
            seen["degraded_last_steps"] = entry["training_steps"]
        if (not seen["cycle_done"]
                and res.get("circuit_opens", 0) >= 1
                and res.get("circuits_open", 1) == 0
                and entry["training_steps"] > 0):
            # full cycle observed: opened at least once, all re-attached,
            # learner trained — drain-then-save stop
            seen["cycle_done"] = True
            os.kill(os.getpid(), signal.SIGTERM)

    m = train(cfg, env_factory=make_fake_env, max_wall_seconds=420,
              verbose=False, log_sink=sink)
    assert seen["cycle_done"], (
        f"freeze→degrade→re-attach cycle never completed: {seen}, "
        f"chaos={m.get('chaos')}")
    assert m["num_updates"] > 0
    assert np.isfinite(m["mean_loss"])
    assert not m["fabric_failed"]
    assert m["chaos"]["freeze_service"] == 1, "the freeze never fired"

    fleet = m["fleet_health"]
    # ZERO fleet deaths: no respawns, no failures — the old behavior was
    # N RuntimeErrors and a burned respawn budget
    assert fleet["restarts"] == [0, 0]
    assert not fleet["failed"]
    res = fleet["resilience"]
    assert res["circuit_opens"] >= 1, "no circuit ever opened"
    assert res["local_acts"] > 0, "no degraded-mode acting happened"
    assert res["retries"] >= 1
    # re-attached: every circuit closed again
    assert res["circuits_open"] == 0
    # the re-attach probes resynced server hidden from the fleet carries
    assert fleet["service"]["resyncs"] >= 1
    # the degraded window was observable in the health stream, and the
    # learner kept updating through it (updates/s > 0 while degraded)
    assert seen["degraded_entries"] >= 1
    assert fleet["blocks_ingested"] > 0
    assert all(c > 0 for c in fleet["blocks_per_fleet"])


# ----------------------------------------------- anakin wedge_dispatch

@pytest.mark.timeout(600)
@pytest.mark.chaos
# the slow-wedge grade is slow-marked (ISSUE 15 wall-budget rebalance):
# it shares every code path with the hard grade except the one extra
# bounded-join grace window, and the alternating chaos_soak --anakin
# rounds drill both grades end to end
@pytest.mark.parametrize("wedge_dur", [
    1.2, pytest.param(0.45, marks=pytest.mark.slow)],
                         ids=["hard", "slow"])
def test_anakin_wedge_dispatch_snapshots_and_aborts(tmp_path, wedge_dur):
    """The deferred anakin chaos site: a wedged dispatch (harvest stalled
    past cfg.dispatch_deadline) must produce a RESUMABLE snapshot and a
    clean abort — not a hang (this test runs under the suite's pytest
    timeout) and not an endless crawl on a flaky device.  --resume then
    continues from the parked state.

    Both wedge grades are drilled: ``dur=1.2`` outlasts the 2x-budget
    grace (hard wedge — fetch abandoned, bounded snapshot), ``dur=0.45``
    lands inside it (slow wedge — the fetch completes over budget, the
    pipeline drains and the snapshot is written inline)."""
    from r2d2_tpu.checkpoint import Checkpointer
    from r2d2_tpu.train import train

    ck = str(tmp_path / "ck")
    cfg = make_test_config(
        game_name="Fake", actor_transport="anakin",
        device_replay=True, in_graph_per=True,
        num_actors=2, superstep_k=2, anakin_episode_len=12,
        training_steps=1000, learning_starts=16, log_interval=0.2,
        dispatch_deadline=0.3,
        chaos_spec=f"wedge_dispatch:at=3,dur={wedge_dur}")
    m = train(cfg, checkpoint_dir=ck, verbose=False,
              max_wall_seconds=240)
    assert m["dispatch_wedged"] is True, "the deadline never tripped"
    assert m["chaos"]["wedge_dispatch"] == 1
    assert 0 < m["num_updates"] < cfg.training_steps  # aborted early
    assert not m["fabric_failed"]                     # CLEAN abort
    # the resumable artifact: a full anakin loop snapshot was parked
    assert Checkpointer(ck).replay_steps(), "no snapshot at the wedge"

    # and --resume genuinely continues from it (no wedge this time)
    m2 = train(cfg.replace(chaos_spec="",
                           training_steps=m["num_updates"] + 4),
               checkpoint_dir=ck, resume=True, verbose=False,
               max_wall_seconds=240)
    assert m2["restored_replay"], "resume came up cold"
    assert m2["dispatch_wedged"] is False
    assert m2["num_updates"] >= m["num_updates"] + 4


# ------------------------------------------------- three-state healthz

def test_healthz_three_state_contract():
    """ok → HTTP 200 status "ok"; degraded → HTTP 200 status "degraded"
    (a degraded instance still serves — evicting it would defeat
    graceful degradation); failing → HTTP 503.  r2d2_top renders the
    degraded verdict."""
    import json
    import os
    import urllib.error
    import urllib.request

    from r2d2_tpu.telemetry import MetricsRegistry, TelemetryExporter

    health = dict(ok=True, degraded=False, status="ok")
    ex = TelemetryExporter(MetricsRegistry(), lambda: dict(health), port=0)

    def loop():
        while not ex.closed:
            try:
                ex.handle_once()
            except (OSError, ValueError):
                return

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{ex.port}"
    try:
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "ok"
        health.update(degraded=True, status="degraded")
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.status == 200                 # still serving
            body = json.loads(resp.read())
            assert body["status"] == "degraded" and body["degraded"]
        health.update(ok=False, degraded=False, status="failing")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "failing"
    finally:
        ex.close()

    # r2d2_top renders the degraded state distinctly
    import importlib.util

    top_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "r2d2_top.py")
    spec = importlib.util.spec_from_file_location("r2d2_top_res", top_path)
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    entry = dict(training_steps=1, updates_per_sec=1.0, buffer_size=1,
                 env_steps=1, mean_episode_return=0.0, mean_loss=0.0,
                 fleet=dict(alive=2, fleets=2, restarts=[0, 0],
                            blocks_ingested=1, blocks_corrupt=0,
                            resilience=dict(circuits_open=1,
                                            circuit_opens=2, retries=3,
                                            local_acts=9,
                                            max_stale_params_s=0.0)))
    frame = top.render(entry, health=dict(ok=True, status="degraded",
                                          threads={}))
    assert "** DEGRADED **" in frame
    assert "circuits_open=1" in frame
    frame_ok = top.render(entry, health=dict(ok=True, status="ok",
                                             threads={}))
    assert "DEGRADED" not in frame_ok.splitlines()[1]
