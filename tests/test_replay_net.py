"""Cross-host replay fabric (replay/netwire.py + parallel/replay_net.py).

The load-bearing claims, each pinned here:

- **Socket-transport parity**: with healthy links the sampled batch
  stream is distribution-equivalent to the shm plane / K=1 oracle
  (TV < 0.05) and the response rows are BIT-EXACT vs shard-local
  gathers — the wire changes nothing about content.
- **Partition tolerance**: a partitioned link's mass leaves the
  gossiped view and its strata redistribute (zero learner stalls); a
  SIGSTOPped shard's rows redistribute within the RPC deadline; ingest
  to an unreachable shard drops-with-count, never wedges the sink.
- **Epoch/reconnect handshake**: a killed-then-respawned-restored shard
  re-attaches mass-exact over the sockets with ZERO duplicate/stale
  feedback applied — the restored ring's leaf multiset is bit-equal to
  the snapshot's (the satellite oracle test).
- **Integrity**: garbled frames are caught by the frame CRC and sample
  responses re-requested by the bounded retry; a geometry-drifted
  endpoint fails the HELLO handshake instead of mis-framing traffic.
"""
import os
import signal
import tempfile
import threading
import time

import numpy as np
import pytest

from r2d2_tpu.checkpoint import Checkpointer
from r2d2_tpu.config import parse_replay_hosts
from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.parallel.replay_net import (
    NET_STAT_FIELDS,
    NetShardedReplayPlane,
    ShardServer,
    shard_slice_config,
)
from r2d2_tpu.replay.block import LocalBuffer
from r2d2_tpu.replay.netwire import (
    NMSG_INGEST,
    NMSG_SAMPLE_RSP,
    layout_token,
    max_net_frame_bytes,
    net_ingest_spec,
    net_sample_response_spec,
)
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.serving.wire import WireGarbled, decode_frame, encode_frame
from r2d2_tpu.utils.chaos import ChaosInjector

A = 4


def make_cfg(**kw):
    kw.setdefault("replay_shards", 2)
    kw.setdefault("replay_transport", "socket")
    kw.setdefault("replay_sample_timeout", 5.0)
    return make_test_config(**kw)


def make_block(cfg, tag, priority):
    local = LocalBuffer(cfg, A)
    local.reset(np.full(cfg.obs_shape, tag % 256, np.uint8))
    for s in range(cfg.block_length):
        obs = np.full(cfg.obs_shape, (tag + s + 1) % 256, np.uint8)
        q = np.arange(A, dtype=np.float32) + s
        hidden = np.full((2, cfg.lstm_layers, cfg.hidden_dim),
                         ((tag + s) % 100) / 100.0, np.float32)
        local.add(s % A, float(s), obs, q, hidden)
    block, _, ep = local.finish(None)
    prios = np.full(cfg.seqs_per_block, priority, np.float32)
    return block, prios, ep


def wait_until(pred, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def fill_plane(plane, cfg, priorities_per_block):
    for b, p in enumerate(priorities_per_block):
        block, prios, ep = make_block(cfg, tag=1000 * b, priority=p)
        plane.add(block, prios, episode_reward=ep)
    want = len(priorities_per_block) * cfg.block_length
    assert wait_until(
        lambda: plane.poll_shard_stats()["size_total"] >= want), \
        plane.poll_shard_stats()


def leaf_masses_oracle(cfg, priorities_per_block):
    """K=1 oracle leaf masses in GLOBAL (sharded) leaf order — block n
    routes to shard n % K, local slot n // K (the shm plane's scheme,
    unchanged on the wire)."""
    K = cfg.replay_shards
    kseq = cfg.seqs_per_block
    lps = cfg.num_sequences // K
    masses = np.zeros(cfg.num_sequences)
    for n, p in enumerate(priorities_per_block):
        s, local_block = n % K, n // K
        lo = s * lps + local_block * kseq
        masses[lo:lo + kseq] = np.float64(np.float32(p)) ** cfg.prio_exponent
    return masses


# ------------------------------------------------------------- wire layer

def test_netwire_frames_roundtrip_and_catch_garble():
    """Ingest and response frames roundtrip bit-exact through the frame
    grammar, and a flipped byte anywhere in the body fails the CRC."""
    cfg = shard_slice_config(make_cfg())
    spec = net_ingest_spec(cfg, A)
    fields = {}
    rng = np.random.default_rng(0)
    for name, shape, dtype in spec:
        if np.issubdtype(np.dtype(dtype), np.floating):
            fields[name] = rng.normal(size=shape).astype(dtype)
        else:
            fields[name] = rng.integers(0, 100, shape).astype(dtype)
    frame = encode_frame(spec, (NMSG_INGEST, 3, 7, 0), fields)
    body = frame[4:]
    header, views = decode_frame(spec, body)
    assert header == (NMSG_INGEST, 3, 7, 0)
    for name, _, _ in spec:
        np.testing.assert_array_equal(views[name], fields[name], name)
    # one flipped byte mid-payload: the frame CRC must catch it
    garbled = bytearray(body)
    garbled[len(garbled) // 2] ^= 0xFF
    with pytest.raises(WireGarbled):
        decode_frame(spec, bytes(garbled))

    # the response spec mirrors the shm slab's row fields exactly
    rsp = net_sample_response_spec(cfg, A, cfg.batch_size)
    names = {n for n, _, _ in rsp}
    assert {"obs", "prios", "idxes", "ages", "rsp_n", "rsp_block_ptr",
            "rsp_env_steps"} <= names
    assert not {"req_seq", "req_crc", "rsp_seq", "rsp_crc"} & names
    assert NMSG_SAMPLE_RSP != NMSG_INGEST


def test_layout_token_detects_geometry_drift():
    cfg = shard_slice_config(make_cfg())
    assert layout_token(cfg, A) == layout_token(cfg, A)
    assert layout_token(cfg, A) != layout_token(
        cfg.replace(batch_size=cfg.batch_size * 2), A)
    assert layout_token(cfg, A) != layout_token(cfg, A + 1)
    assert max_net_frame_bytes(cfg, A) > 0


def test_net_stat_fields_extend_shard_schema():
    names = [n for n, _ in NET_STAT_FIELDS]
    assert "tree_mass" in names and "incarnation" in names
    for extra in ("epoch_drops", "net_garbled", "prio_batches"):
        assert extra in names


# ------------------------------------------------------------ validation

def test_config_validation_and_host_parsing():
    with pytest.raises(ValueError, match="replay_transport"):
        make_test_config(replay_transport="carrier-pigeon")
    with pytest.raises(ValueError, match="replay_hosts"):
        make_test_config(replay_hosts="h:1")   # shm transport
    with pytest.raises(ValueError, match="device_replay"):
        make_cfg(device_replay=True, in_graph_per=False)
    with pytest.raises(ValueError, match="anakin"):
        make_cfg(actor_transport="anakin")
    with pytest.raises(ValueError, match="one "):
        make_cfg(replay_hosts="127.0.0.1:1")   # 1 host, 2 shards
    with pytest.raises(ValueError, match="host:port"):
        make_cfg(replay_hosts="nocolon,alsono")
    with pytest.raises(ValueError, match="port out of range"):
        # 0 is the managed plane's not-yet-spawned sentinel, never a
        # valid connect target — must fail at construction
        make_cfg(replay_hosts="127.0.0.1:0,127.0.0.1:0")
    with pytest.raises(ValueError, match="replay_net_cooldown"):
        make_cfg(replay_net_cooldown=0.0)
    with pytest.raises(ValueError, match="replay_net_send_budget"):
        make_cfg(replay_net_send_budget=-1.0)
    assert parse_replay_hosts("a:1, b:2") == [("a", 1), ("b", 2)]
    ok = make_cfg(replay_hosts="127.0.0.1:7001,127.0.0.1:7002")
    assert ok.replay_transport == "socket"
    # the new chaos kinds parse
    from r2d2_tpu.utils.chaos import parse_spec

    spec = parse_spec("partition_shard_link:every=10,dur=1.5;"
                      "delay_shard_link:p=0.5,dur=0.2;"
                      "half_open_shard:at=3,dur=1;"
                      "garble_net_frame:p=0.01")
    assert set(spec) == {"partition_shard_link", "delay_shard_link",
                         "half_open_shard", "garble_net_frame"}
    inj = ChaosInjector("partition_shard_link:at=2,dur=1.5;"
                        "garble_net_frame:every=2", seed=0)
    assert inj.net_partition_seconds() == 0.0
    assert inj.net_partition_seconds() == 1.5
    assert inj.net_partition_seconds() == 0.0
    assert [inj.garble_net_frame() for _ in range(4)] \
        == [False, True, False, True]


def test_cli_replay_shard_rejects_bad_shard_id():
    from r2d2_tpu import cli as cli_mod

    with pytest.raises(SystemExit):
        cli_mod.main(["replay-shard", "--preset", "test", "--game",
                      "Fake", "--port", "0", "--shard-id", "5",
                      "--replay-shards", "2", "--action-dim", "4"])


# ------------------------------------------------------ plane end-to-end

def test_socket_parity_bit_exact_rows_and_mass_conservation():
    """Ingest → sample → feedback over real sockets vs the K=1 oracle
    fed the identical stream: response rows BIT-EXACT vs shard-local
    gathers, mass conserved through the cycle, per-shard snapshot leaf
    multiset bit-equal to the oracle's."""
    cfg = make_cfg()
    prios_per_block = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]
    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(0))
    plane.start()
    try:
        fill_plane(plane, cfg, prios_per_block)
        oracle = ReplayBuffer(cfg.replace(replay_shards=1,
                                          replay_transport="shm"), A,
                              rng=np.random.default_rng(0))
        for b, p in enumerate(prios_per_block):
            block, prios, ep = make_block(cfg, tag=1000 * b, priority=p)
            oracle.add(block, prios, ep)
        st = plane.poll_shard_stats()
        assert np.isclose(st["mass_total"], oracle.tree.total, rtol=1e-12)

        batch = plane.sample_batch(8)
        assert batch is not None
        assert batch["idxes"].shape == (8,)
        # the pipeline: the NEXT draw's requests went out before this
        # batch returned (two in flight per link while the learner runs)
        assert plane._pending_draw is not None

        K, kseq = cfg.replay_shards, cfg.seqs_per_block
        lps = cfg.num_sequences // K
        shard = batch["idxes"] // lps
        local = batch["idxes"] % lps
        logical_block = (local // kseq) * K + shard
        oracle_idx = logical_block * kseq + (local % kseq)
        # BIT-EXACT rows vs the oracle's gather for the same content —
        # pins the whole shard-side gather + frame + concat path
        with oracle.lock:
            want_rows = oracle._gather_rows(oracle_idx)
        for name, arr in want_rows.items():
            np.testing.assert_array_equal(batch[name], arr, err_msg=name)

        new_prios = np.linspace(0.5, 4.0, 8).astype(np.float64)
        plane.update_priorities(batch["idxes"], new_prios,
                                batch["block_ptr"], loss=0.25)
        oracle.update_priorities(oracle_idx, new_prios,
                                 oracle.block_ptr, loss=0.25)

        def fed_back():
            t = plane.poll_shard_stats()["totals"]
            return t.get("prio_updates", 0) >= 2
        assert wait_until(fed_back)
        st2 = plane.poll_shard_stats()
        assert np.isclose(st2["mass_total"], oracle.tree.total,
                          rtol=1e-12)
        s = plane.stats()
        assert s["training_steps"] == 1 and s["sum_loss"] == 0.25

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "ring.bin")
            meta = plane.write_state(path)
            assert meta["kind"] == "sharded" and meta["shards"] == 2
            leaves = []
            for sh in range(2):
                shard_buf = ReplayBuffer(plane.shard_cfg, A)
                shard_buf.read_state(f"{path}.shard{sh}",
                                     meta["shard_metas"][sh])
                leaves.append(shard_buf.tree.leaf_values())
            got = np.sort(np.concatenate(leaves))
            want = np.sort(oracle.tree.leaf_values())
            np.testing.assert_array_equal(got, want)
        # shard-side feedback batching is live and counted
        assert plane.health()["net"]["prio_batches"] >= 1
    finally:
        plane.shutdown()


def _empirical_content_freq(sampler, cfg, draws, batch):
    counts = np.zeros(cfg.num_sequences)
    for _ in range(draws):
        idx = sampler(batch)
        counts[idx] += 1
    return counts / counts.sum()


def test_socket_draw_distribution_matches_oracle_under_skew():
    """The parity acceptance: even with one shard holding ~all the
    priority mass, the socket plane's sampled-content distribution
    matches the exact K=1 marginal (TV < 0.05) — the wire is invisible
    to the sampling math."""
    cfg = make_cfg()
    prios_per_block = [50.0 if b % 2 == 0 else 1e-3 for b in range(8)]
    expected = leaf_masses_oracle(cfg, prios_per_block)
    expected = expected / expected.sum()

    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(1))
    plane.start()
    try:
        fill_plane(plane, cfg, prios_per_block)
        mass_share = plane.poll_shard_stats()["masses"]
        assert mass_share[0] / mass_share.sum() > 0.99
        freq = _empirical_content_freq(
            lambda b: plane.sample_batch(b)["idxes"], cfg, 250, 8)
    finally:
        plane.shutdown()
    tv = 0.5 * np.abs(freq - expected).sum()
    assert tv < 0.05, (tv, freq, expected)


def test_partitioned_link_redistributes_drops_ingest_and_heals():
    """The partition drill at the plane layer: a blackholed link's mass
    leaves the gossiped view (stale gossip — no RPC ever has to time
    out), its strata redistribute to the survivor, ingest routed to it
    drops-with-count, and after the heal the shard serves again with no
    stale response ever entering a batch."""
    cfg = make_cfg(replay_sample_timeout=1.0)
    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(2))
    plane.start()
    try:
        fill_plane(plane, cfg, [1.0] * 8)
        # consume the warm prefetch issued against the healthy view,
        # then partition: later draws see the stale-gossip mask
        assert plane.sample_batch(8) is not None
        plane.links[0].partition_for(4.5)
        assert wait_until(lambda: not plane.links[0].stats_fresh(), 10.0)
        lps = cfg.num_sequences // cfg.replay_shards
        # a prefetched draw may still carry shard-0 rows RECEIVED before
        # the partition (valid data); within a draw or two the stale
        # view must route everything to the survivor — and no draw may
        # ever stall (each returns a batch or None promptly)

        def survivor_only():
            b = plane.sample_batch(8)
            return b is not None and (b["idxes"] // lps == 1).all()
        assert wait_until(survivor_only, 2.2, interval=0.01), \
            "partitioned shard kept receiving strata"
        # ingest routed to the partitioned shard is dropped + counted
        drops0 = plane.dropped_blocks
        for b in range(4):
            block, prios, ep = make_block(cfg, tag=9000 + b, priority=1.0)
            plane.add(block, prios, ep)
        assert plane.dropped_blocks >= drops0 + 2
        # heal: the link was never torn down (a partition is not a
        # close) — gossip refreshes and both shards serve again
        assert wait_until(lambda: plane.links[0].stats_fresh(), 15.0)

        def both_serve():
            b = plane.sample_batch(8)
            return b is not None and len(np.unique(b["idxes"] // lps)) == 2
        assert wait_until(both_serve, 15.0)
        assert plane.health()["net"]["partitions"] == 0  # direct, not chaos
    finally:
        plane.shutdown()


def test_sigstop_then_half_open_redistribute_and_recover():
    """Two wire faults through ONE plane session.  Phase 1 — SIGSTOP a
    managed shard server: the sample RPC deadline fires and its rows
    redistribute over the survivor's mass (a full batch, zero learner
    stalls, counted as timeouts + redraws), and after SIGCONT it serves
    again.  Phase 2 — half-open the recovered link (sends silently
    lost): the deadline fires again, rows redistribute, and after the
    window the probe/reconnect re-closes the circuit and both shards
    serve."""
    cfg = make_cfg(replay_sample_timeout=0.5, replay_net_cooldown=0.5)
    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(4))
    plane.start()
    lps = cfg.num_sequences // cfg.replay_shards
    try:
        fill_plane(plane, cfg, [1.0] * 8)
        os.kill(plane.procs[0].pid, signal.SIGSTOP)
        try:
            t0 = time.time()
            batch = plane.sample_batch(8)
            if batch is None:
                batch = plane.sample_batch(8)
            elapsed = time.time() - t0
        finally:
            os.kill(plane.procs[0].pid, signal.SIGCONT)
        assert batch is not None and batch["idxes"].shape == (8,)
        assert (batch["idxes"] // lps == 1).all()
        assert plane.sample_timeouts + plane.redraws >= 1
        assert elapsed < 8 * cfg.replay_sample_timeout + 4.0

        def both_serve():
            b = plane.sample_batch(8)
            return (b is not None
                    and len(np.unique(b["idxes"] // lps)) == 2)
        assert wait_until(both_serve, 15.0)

        # phase 2: half-open the recovered link — lost requests time
        # out, rows redistribute to the survivor, then the probe (or
        # the torn-down reconnect) re-attaches
        timeouts0 = plane.sample_timeouts
        plane.links[0].half_open_for(1.5)

        def survivor_only():
            b = plane.sample_batch(8)
            return b is not None and (b["idxes"] // lps == 1).all()
        assert wait_until(survivor_only, 5.0, interval=0.01)
        assert plane.sample_timeouts > timeouts0
        assert wait_until(both_serve, 20.0)
    finally:
        plane.shutdown()


def test_garbled_net_frames_are_caught_and_retried():
    """garble_net_frame chaos flips received frame bytes ahead of
    decode: the frame CRC must catch every one and the bounded retry
    must still assemble full batches."""
    cfg = make_cfg()
    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(5))
    plane.chaos = ChaosInjector("garble_net_frame:every=15", seed=7)
    plane.start()
    try:
        fill_plane(plane, cfg, [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0])
        got = 0
        for _ in range(8):
            batch = plane.sample_batch(8)
            if batch is not None:
                got += 1
                assert batch["idxes"].shape == (8,)
        assert got >= 5
        h = plane.health()
        caught = (sum(row["garbled"] for row in h["net"]["links"])
                  + h["net"]["shard_garbled"])
        assert caught >= 1
    finally:
        plane.shutdown()


# --------------------------------------------- the epoch/reconnect oracle

def test_kill_respawn_over_sockets_mass_exact_zero_stale_feedback():
    """THE satellite acceptance: kill a shard server, let the watchdog
    respawn it restored from the latest committed snapshot, and prove —
    over real sockets — that (a) the restored ring is MASS-EXACT
    (bit-equal leaf multiset vs the snapshot it restored from), (b) the
    re-attach went through the epoch handshake (new epoch on the link),
    and (c) feedback sampled before the kill applied ZERO rows to the
    restored ring (dropped-and-counted trainer-side; the shard's own
    epoch gate stops anything that slips through)."""
    cfg = make_cfg(replay_sample_timeout=2.0)
    prios_per_block = [4.0, 1.0, 2.0, 3.0, 5.0, 2.5, 1.5, 0.5]
    plane = NetShardedReplayPlane(cfg, A, rng=np.random.default_rng(3))
    plane.start()
    try:
        fill_plane(plane, cfg, prios_per_block)
        pre = plane.poll_shard_stats()

        with tempfile.TemporaryDirectory() as d:
            ck = Checkpointer(d)
            ck.save_replay(0, plane.write_state)
            plane.checkpointer = ck
            snap_meta, snap_ring, _ = ck.restore_replay()

            batch = plane.sample_batch(8)   # pre-kill sample → stale epoch
            assert batch is not None
            victim = 0
            epoch_before = plane.links[victim].epoch

            plane.procs[victim].kill()
            assert wait_until(
                lambda: not plane.procs[victim].is_alive(), 10.0)
            assert plane.watch_once() == 1
            assert plane.restarts[victim] == 1

            # cross-respawn feedback for the victim is dropped; the
            # survivor's share still applies
            plane.update_priorities(batch["idxes"],
                                    np.ones(8, np.float64),
                                    batch["block_ptr"], loss=0.0)
            lps = cfg.num_sequences // cfg.replay_shards
            victim_rows = int((batch["idxes"] // lps == victim).sum())
            assert plane.stale_feedback == victim_rows

            # the respawn re-attached through the epoch handshake
            assert wait_until(
                lambda: plane.links[victim].connected, 30.0)
            assert plane.links[victim].epoch != epoch_before

            # restored mass is EXACT (the survivor's changed only by its
            # fed-back rows, so compare the victim's shard alone)
            def restored():
                st = plane.poll_shard_stats()
                return np.isclose(st["masses"][victim],
                                  pre["masses"][victim], rtol=0, atol=0)
            assert wait_until(restored, 40.0), (
                plane.poll_shard_stats()["masses"], pre["masses"])
            assert plane.stats()["shard_respawns"] == 1

            # bit-equal leaf multiset: snapshot the respawned plane and
            # compare the victim's leaves against the snapshot it
            # restored from — zero stale feedback ever landed
            path2 = os.path.join(d, "ring2.bin")
            meta2 = plane.write_state(path2)
            buf_restored = ReplayBuffer(plane.shard_cfg, A)
            buf_restored.read_state(f"{path2}.shard{victim}",
                                    meta2["shard_metas"][victim])
            buf_snap = ReplayBuffer(plane.shard_cfg, A)
            buf_snap.read_state(f"{snap_ring}.shard{victim}",
                                snap_meta["shard_metas"][victim])
            np.testing.assert_array_equal(
                np.sort(buf_restored.tree.leaf_values()),
                np.sort(buf_snap.tree.leaf_values()))

            # the plane still samples full batches post-restore
            b2 = plane.sample_batch(8)
            if b2 is None:
                b2 = plane.sample_batch(8)
            assert b2 is not None and b2["idxes"].shape == (8,)
            # the link's reconnect is counted in the net health table
            assert plane.health()["net"]["links"][victim]["attaches"] >= 2
    finally:
        plane.shutdown()


# ----------------------------------------------------- remote-attach mode

def test_standalone_servers_attach_mode_and_cold_resume_contract():
    """Attach mode: the trainer connects to already-running shard
    servers (the `r2d2_tpu replay-shard` deployment) — ingest, sample
    and feedback flow over the same wire path, and a full-state resume
    raises the documented cold-resume ValueError (remote shards restore
    from their own host-local snapshots)."""
    cfg = make_cfg()
    shard_cfg = shard_slice_config(cfg)
    servers = [ShardServer(shard_cfg, A, s, epoch=100 + s) for s in (0, 1)]
    stop = {"flag": False}
    threads = [
        threading.Thread(  # graftlint: disable=thread-discipline -- test harness server pump, flag-stopped + joined below
            target=srv.serve_forever, args=(lambda: stop["flag"],),
            daemon=True)
        for srv in servers]
    for t in threads:
        t.start()
    hosts = ",".join(f"127.0.0.1:{srv.port}" for srv in servers)
    plane = NetShardedReplayPlane(cfg.replace(replay_hosts=hosts), A,
                                  rng=np.random.default_rng(0))
    try:
        plane.start()
        assert not plane.managed
        fill_plane(plane, cfg, [1.0, 2.0, 3.0, 4.0])
        batch = plane.sample_batch(8)
        assert batch is not None and batch["idxes"].shape == (8,)
        assert plane.links[0].epoch == 100
        with pytest.raises(ValueError, match="host-local"):
            plane.read_state("whatever", dict(kind="sharded", shards=2))
        # watch_once is a no-op in attach mode (no procs to respawn)
        assert plane.watch_once() == 0
    finally:
        plane.shutdown()
        stop["flag"] = True
        for t in threads:
            t.join(10.0)
        for srv in servers:
            srv.close()


def test_handshake_rejects_geometry_drift():
    """A trainer built from a drifted config must fail the HELLO
    handshake (WELCOME epoch −1 → fatal link), never mis-frame."""
    cfg = make_cfg()
    srv = ShardServer(shard_slice_config(cfg), A, 0, epoch=1)
    stop = {"flag": False}
    t = threading.Thread(  # graftlint: disable=thread-discipline -- test harness server pump, flag-stopped + joined below
        target=srv.serve_forever, args=(lambda: stop["flag"],),
        daemon=True)
    t.start()
    drifted = make_cfg(batch_size=16,
                       replay_hosts=f"127.0.0.1:{srv.port},"
                                    f"127.0.0.1:{srv.port}")
    plane = NetShardedReplayPlane(drifted, A)
    try:
        with pytest.raises(RuntimeError, match="rejected the attach"):
            plane.start(wait_ready=20.0)
    finally:
        plane.shutdown()
        stop["flag"] = True
        t.join(10.0)
        srv.close()


# --------------------------------------------------------- train() layer

def _env_factory(cfg, seed):
    from r2d2_tpu.envs.fake import FakeAtariEnv

    return FakeAtariEnv(obs_shape=cfg.obs_shape, action_dim=A, seed=seed,
                        episode_len=24)


@pytest.mark.chaos
@pytest.mark.slow
def test_train_socket_replay_with_partition_kill_and_garble(tmp_path):
    """The acceptance drill: a socket-replay train() round with a link
    partition, a shard kill and frame garbling armed completes with
    zero learner stalls, the watchdog respawns the shard through the
    epoch handshake, accounting stays conserved (every learner update
    reached the plane), and the replay.net.* surface lands in the
    run's telemetry.

    Marked slow: tier-1 already pins every claim here at the plane
    layer (partition/kill/garble tests above) and the committed
    ``chaos_soak --nethost`` artifact covers the train()-level
    composition — this full-fabric round rides the slow suite to keep
    tier-1 inside its wall budget."""
    from r2d2_tpu.train import train

    cfg = make_test_config(
        game_name="Fake", replay_shards=2, replay_transport="socket",
        training_steps=40, log_interval=0.5, learning_starts=16,
        replay_sample_timeout=1.0, replay_net_cooldown=0.5,
        learner_stall_timeout=60.0,
        chaos_spec=("kill_replay_shard:at=4;"
                    "partition_shard_link:at=6,dur=1.5;"
                    "garble_net_frame:every=40,n=1000000"))
    m = train(cfg, env_factory=_env_factory, checkpoint_dir=str(tmp_path),
              verbose=False, max_wall_seconds=180)
    assert m["num_updates"] > 0
    assert not m["learner_stalled"]
    assert not m["fabric_failed"]
    rh = m["replay_shard_health"]
    assert m["chaos"].get("kill_replay_shard", 0) == 1
    assert m["chaos"].get("partition_shard_link", 0) == 1
    assert sum(rh["respawns"]) >= 1
    assert rh["alive"] == 2                  # the victim came back
    assert rh["net"]["connected"] == 2       # links healed
    assert rh["net"]["partitions"] == 1
    # conserved accounting: every learner update reached the plane
    assert m["buffer_training_steps"] == m["num_updates"]
    entry = m["logs"][-1]
    assert entry["replay_shards"]["shards"] == 2
    assert entry["replay_shards"]["net"]["transport"] == "socket"
