"""Test harness: force an 8-device virtual CPU mesh.

This is the JAX-native way to test multi-chip sharding without hardware
(SURVEY.md §4): all tests run on CPU with 8 fake devices so pjit/Mesh code
paths execute real collectives.

Two mechanisms, both needed:
- ``XLA_FLAGS`` must be in the environment before the CPU backend
  initialises (it is read at backend-init time, which happens lazily at the
  first jax op inside a test).
- ``jax.config.update("jax_platforms", "cpu")`` rather than the
  ``JAX_PLATFORMS`` env var: this session's interpreter is pre-warmed with
  jax already imported and pinned to the tunneled TPU platform, so the env
  var is read too late; the config update still works post-import.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests may spawn

import jax

jax.config.update("jax_platforms", "cpu")
