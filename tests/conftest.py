"""Test harness: force an 8-device virtual CPU mesh.

This is the JAX-native way to test multi-chip sharding without hardware
(SURVEY.md §4): all tests run on CPU with 8 fake devices so pjit/Mesh code
paths execute real collectives.

Two mechanisms, both needed:
- ``XLA_FLAGS`` must be in the environment before the CPU backend
  initialises (it is read at backend-init time, which happens lazily at the
  first jax op inside a test).
- ``jax.config.update("jax_platforms", "cpu")`` rather than the
  ``JAX_PLATFORMS`` env var: this session's interpreter is pre-warmed with
  jax already imported and pinned to the tunneled TPU platform, so the env
  var is read too late; the config update still works post-import.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests may spawn

import jax

jax.config.update("jax_platforms", "cpu")

# --- per-test timeout fallback ------------------------------------------
# pyproject.toml sets `timeout = 300` for pytest-timeout; when the plugin
# is not installed (this image cannot pip install), emulate its "thread"
# method with faulthandler: a test exceeding the budget dumps EVERY
# thread's stack and kills the run — a queue-wedge bug fails fast with a
# diagnosis instead of silently eating the CI wall clock.
try:
    import pytest_timeout  # noqa: F401

    _HAVE_TIMEOUT_PLUGIN = True
except ImportError:
    _HAVE_TIMEOUT_PLUGIN = False

if not _HAVE_TIMEOUT_PLUGIN:
    import faulthandler
    import sys
    import threading

    import pytest

    def pytest_addoption(parser):
        parser.addini("timeout",
                      "fallback per-test timeout in seconds (0 disables); "
                      "normally owned by pytest-timeout", default="0")

    @pytest.fixture(autouse=True)
    def _fallback_test_timeout(request):
        try:
            budget = float(request.config.getini("timeout") or 0)
        except (TypeError, ValueError):
            budget = 0.0
        marker = request.node.get_closest_marker("timeout")
        if marker and marker.args:
            budget = float(marker.args[0])
        if budget <= 0:
            yield
            return

        def on_timeout():
            # suspend capture first (pytest-timeout's thread method does
            # the same) or the dump lands in a discarded capture tempfile
            capman = request.config.pluginmanager.getplugin(
                "capturemanager")
            if capman is not None:
                try:
                    capman.suspend_global_capture(in_=True)
                except Exception:
                    pass
            sys.stderr.write(
                f"\n+++ timeout: {request.node.nodeid} exceeded "
                f"{budget:.0f}s — dumping all thread stacks +++\n")
            faulthandler.dump_traceback(file=sys.stderr)
            sys.stderr.flush()
            os._exit(1)

        timer = threading.Timer(budget, on_timeout)
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()
