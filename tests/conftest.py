"""Test harness: force an 8-device virtual CPU mesh before jax imports.

This is the JAX-native way to test multi-chip sharding without hardware
(SURVEY.md §4): all tests run on CPU with 8 fake devices so pjit/Mesh code
paths execute real collectives.
"""
import os

# Force CPU: the session environment pins JAX_PLATFORMS=axon (the tunneled
# TPU), but tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
