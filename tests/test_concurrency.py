"""Race-safety stress tests (SURVEY.md §5.2): the three data planes
(block ingest / batch sampling / priority feedback) hammering one
ReplayBuffer concurrently, and concurrent ParamStore publish/get.

The reference tolerates torn weight reads and serialises the buffer with
one lock (worker.py:65); here the invariants under contention are
asserted, not assumed.
"""
import threading

import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.envs.fake import FakeAtariEnv
from r2d2_tpu.replay.block import LocalBuffer
from r2d2_tpu.replay.replay_buffer import ReplayBuffer
from r2d2_tpu.utils.store import ParamStore


def _make_block(cfg, action_dim, rng, steps=None):
    """Drive a LocalBuffer through a short fake episode to a real Block."""
    env = FakeAtariEnv(obs_shape=cfg.stored_obs_shape, action_dim=action_dim,
                       seed=int(rng.integers(1 << 31)))
    lb = LocalBuffer(cfg, action_dim)
    obs, _ = env.reset()
    lb.reset(obs)
    steps = steps or cfg.block_length
    for _ in range(steps):
        a = int(rng.integers(action_dim))
        obs, r, term, trunc, _ = env.step(a)
        q = rng.random(action_dim).astype(np.float32)
        hidden = np.zeros((2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
        lb.add(a, float(r), obs, q, hidden)
        if term or trunc or len(lb) == cfg.block_length:
            return lb.finish(None if (term or trunc) else q)
    return lb.finish(rng.random(action_dim).astype(np.float32))


def test_concurrent_add_sample_update_priorities():
    cfg = make_test_config(buffer_capacity=320, learning_starts=32)
    A = 4
    rng = np.random.default_rng(0)
    buf = ReplayBuffer(cfg, A, rng=np.random.default_rng(1))

    # pre-fill past readiness
    while not buf.ready:
        buf.add(*_make_block(cfg, A, rng))

    errors = []
    stop = threading.Event()

    def guard(fn):
        def run():
            local = np.random.default_rng(threading.get_ident() % (1 << 31))
            try:
                while not stop.is_set():
                    fn(local)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
        return run

    def add_plane(local):
        buf.add(*_make_block(cfg, A, np.random.default_rng(
            int(local.integers(1 << 31)))))

    sampled = []

    def sample_plane(local):
        batch = buf.sample_batch()
        assert batch["obs"].shape[0] == cfg.batch_size
        assert (batch["learning"] >= 1).all()
        sampled.append((batch["idxes"], batch["block_ptr"]))

    def update_plane(local):
        if not sampled:
            return
        idxes, ptr = sampled.pop()
        prios = local.random(len(idxes)).astype(np.float32) + 1e-3
        buf.update_priorities(idxes, prios, ptr, float(local.random()))

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (add_plane, add_plane, sample_plane, update_plane)]
    for t in threads:
        t.start()
    import time

    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(5.0)

    assert not errors, errors[:1]
    # buffer invariants survived the contention
    s = buf.stats()
    assert 0 < s["size"] <= cfg.buffer_capacity
    batch = buf.sample_batch()
    assert np.isfinite(batch["is_weights"]).all()
    assert (batch["is_weights"] > 0).all()


def test_paramstore_concurrent_publish_get_versions_monotonic():
    store = ParamStore()
    store.publish({"w": np.zeros(4)})
    errors = []
    stop = threading.Event()

    def publisher():
        v = 0
        try:
            while not stop.is_set():
                v += 1
                store.publish({"w": np.full(4, float(v))})
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        last = -1
        try:
            while not stop.is_set():
                version, params = store.get()
                assert version >= last, "version went backwards"
                # snapshot consistency: all entries carry one value
                assert len(set(np.asarray(params["w"]).tolist())) == 1
                last = version
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=publisher, daemon=True)] + [
        threading.Thread(target=reader, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    import time

    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(3.0)
    assert not errors, errors[:1]


def test_param_store_placed_cache_shared_across_consumers():
    """get_placed computes one placement per (version, device) and shares
    it — the multi-fleet actor plane must not pay one transfer per fleet."""
    import jax

    from r2d2_tpu.utils.store import ParamStore

    dev = jax.devices("cpu")[0]
    store = ParamStore()
    v0, p0 = store.get_placed(dev)
    assert v0 == 0 and p0 is None  # nothing published yet

    store.publish({"w": jax.numpy.ones((4,))})
    v1, p1 = store.get_placed(dev)
    v1b, p1b = store.get_placed(dev)
    assert v1 == v1b == 1
    assert p1 is p1b  # cached object, not a fresh transfer

    store.publish({"w": jax.numpy.zeros((4,))})
    v2, p2 = store.get_placed(dev)
    assert v2 == 2 and p2 is not p1
    import numpy as np
    np.testing.assert_array_equal(np.asarray(p2["w"]), 0.0)

def test_param_store_placed_cache_dropped_on_publish():
    """publish must drop the previous generation's placements — stale
    per-device copies would otherwise be pinned forever after their
    consumers exit (e.g. actor close in long-lived embedding processes)."""
    import jax

    from r2d2_tpu.utils.store import ParamStore

    dev = jax.devices("cpu")[0]
    store = ParamStore({"w": jax.numpy.ones((4,))})
    store.get_placed(dev)
    assert dev in store._placed
    store.publish({"w": jax.numpy.zeros((4,))})
    assert store._placed == {}  # old generation released immediately
    store.get_placed(dev)
    assert list(store._placed) == [dev]
