"""Learning-health plane (telemetry/learnhealth.py, ISSUE 13).

Coverage map:
- the in-graph diagnostic bundle pinned against HOST-SIDE ORACLES: a
  pure-numpy re-unroll of the mlp+LSTM network from a zero state for the
  ΔQ divergence, numpy bucketize for the |TD|/IS histograms (exact
  integer counts), numpy norms for the grad/update/param/target-lag
  fields;
- cadence gating (``lax.cond`` on the step counter) and the disarmed
  program's unchanged arity;
- per-dispatch HOST_TRANSFERS counts UNCHANGED with diagnostics armed
  (the anakin fused loop — the strictest budget in the tree);
- the NaN sentry end to end: poisoned params (chaos ``poison_params``)
  → nonfinite alert row + degraded /healthz + a CLEAN training stop;
- alerts.jsonl resume-append continuity across a stop→resume cycle;
- monitor / alert-engine / data-health units (spike EWMA vs the
  freeze interplay, ESS collapse, replay-ratio band, /alertz).
"""
import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.envs import FakeAtariEnv
from r2d2_tpu.learner.step import (
    _gather_time,
    _window_indices,
    create_train_state,
    loss_and_priorities,
    make_optimizer,
    make_train_step,
)
from r2d2_tpu.models.network import R2D2Network, create_network, init_params
from r2d2_tpu.telemetry.learnhealth import (
    DIAG_SCALARS,
    DIAG_SIZE,
    IS_WEIGHT_EDGES,
    PRIO_EDGES,
    TD_ABS_EDGES,
    _SCALAR_IDX,
    AlertEngine,
    LearnHealthMonitor,
    priority_health,
    read_alerts,
    replay_ratio,
)
from r2d2_tpu.telemetry.registry import MetricsRegistry
from r2d2_tpu.train import train
from r2d2_tpu.utils.batch import synthetic_batch

A = 4


def env_factory(cfg, seed):
    return FakeAtariEnv(obs_shape=cfg.obs_shape, action_dim=A, seed=seed,
                        episode_len=32)


def lh_config(**kw):
    base = dict(learnhealth_interval=1)
    base.update(kw)
    return make_test_config(**base)


def scalar(diag, name):
    return float(np.asarray(diag)[_SCALAR_IDX[name]])


# ---------------------------------------------------------------------------
# the host-side numpy re-unroll oracle (mlp torso + scan LSTM + dueling
# head — the exact op sequence of models/network.py in float32 numpy)
# ---------------------------------------------------------------------------

def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def numpy_unroll(cfg, params, obs, last_action, last_reward, hidden):
    """q (B, T, A) float32 — the R2D2Network.unroll twin for the mlp
    torso, computed entirely in numpy (float32 like the jitted path:
    test_config pins compute_dtype='float32')."""
    p = params["params"]
    B, T = obs.shape[:2]
    H = cfg.hidden_dim
    x = obs.reshape(B * T, -1).astype(np.float32) / np.float32(255.0)
    d0 = p["torso"]["Dense_0"]
    x = np.maximum(x @ np.asarray(d0["kernel"]) + np.asarray(d0["bias"]),
                   0.0)
    feats = np.concatenate(
        [x.reshape(B, T, H), np.asarray(last_action, np.float32),
         np.asarray(last_reward, np.float32)[..., None]], axis=-1)
    xs = feats
    for i in range(cfg.lstm_layers):
        lp = p[f"lstm_{i}"]
        wi, wh = np.asarray(lp["wi"]), np.asarray(lp["wh"])
        b = np.asarray(lp["b"])
        x_proj = xs @ wi + b                       # (B, T, 4H)
        h = np.asarray(hidden[:, 0, i], np.float32)
        c = np.asarray(hidden[:, 1, i], np.float32)
        outs = np.empty((B, T, H), np.float32)
        for t in range(T):
            gates = x_proj[:, t] + h @ wh
            gi, gf, gg, go = np.split(gates, 4, axis=-1)
            c = _sigmoid(gf) * c + _sigmoid(gi) * np.tanh(gg)
            h = _sigmoid(go) * np.tanh(c)
            outs[:, t] = h
        xs = outs
    flat = xs.reshape(B * T, H)

    def dense(sub, x):
        return x @ np.asarray(sub["kernel"]) + np.asarray(sub["bias"])

    adv = dense(p["head"]["adv_out"],
                np.maximum(dense(p["head"]["adv_hidden"], flat), 0.0))
    val = dense(p["head"]["val_out"],
                np.maximum(dense(p["head"]["val_hidden"], flat), 0.0))
    q = val + adv - adv.mean(axis=-1, keepdims=True)
    return q.reshape(B, T, -1).astype(np.float32)


def np_bucketize(values, mask, edges):
    """The registry _Histogram bucket rule (bisect_left) in numpy —
    exact integer counts."""
    idx = np.searchsorted(np.asarray(edges), np.ravel(values),
                          side="left")
    out = np.zeros(len(edges) + 1, np.int64)
    np.add.at(out, idx, np.ravel(mask).astype(np.int64))
    return out


# ---------------------------------------------------------------------------
# the ΔQ / histogram / norm oracles
# ---------------------------------------------------------------------------

def test_diag_matches_host_oracles():
    """One armed step on a synthetic batch: every diagnostic field is
    pinned against an independent host-side recomputation — the ΔQ
    against BOTH a jax re-unroll twin (tight) and the pure-numpy
    re-unroll oracle (f32 matmul tolerance), the histograms exactly."""
    cfg = lh_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(1))
    state = create_train_state(cfg, params)
    batch_np = synthetic_batch(cfg, A, np.random.default_rng(3))
    batch = {k: jnp.asarray(v) for k, v in batch_np.items()}

    step = make_train_step(cfg, net, learnhealth=True)
    new_state, loss, priorities, diag = jax.jit(step)(state, batch)
    diag = np.asarray(jax.device_get(diag))
    assert diag.shape == (DIAG_SIZE,)
    assert scalar(diag, "armed") == 1.0
    assert scalar(diag, "loss") == pytest.approx(float(loss), rel=1e-6)
    assert scalar(diag, "nonfinite") == 0.0

    # --- ΔQ: stored-state vs zero-state re-unroll ---------------------
    def unroll(hid):
        q, _ = net.apply(params, batch["obs"], batch["last_action"],
                         batch["last_reward"], hid,
                         method=R2D2Network.unroll)
        return np.asarray(q)

    q_stored = unroll(batch["hidden"])
    q_zero = unroll(jnp.zeros_like(batch["hidden"]))
    idx_online, _, mask = jax.device_get(_window_indices(
        cfg, batch["burn_in"], batch["learning"], batch["forward"]))
    take = np.take_along_axis
    dq = np.abs(take(q_stored, idx_online[:, :, None], 1)
                - take(q_zero, idx_online[:, :, None], 1))
    dq = np.where(mask[:, :, None], dq, 0.0)
    want_mean = dq.sum() / max(1, mask.sum() * A)
    want_max = dq.max()
    assert want_max > 0   # stored hiddens are nonzero: real divergence
    np.testing.assert_allclose(scalar(diag, "dq_mean"), want_mean,
                               rtol=2e-5)
    np.testing.assert_allclose(scalar(diag, "dq_max"), want_max,
                               rtol=2e-5)

    # the numpy re-unroll oracle: the diag's recompute path really is a
    # from-zero-state unroll of the same network
    q_zero_np = numpy_unroll(cfg, jax.device_get(params), batch_np["obs"],
                             batch_np["last_action"],
                             batch_np["last_reward"],
                             np.zeros_like(batch_np["hidden"]))
    np.testing.assert_allclose(q_zero_np, q_zero, atol=5e-5, rtol=1e-4)
    dq_np = np.abs(take(q_stored, idx_online[:, :, None], 1)
                   - take(q_zero_np, idx_online[:, :, None], 1))
    dq_np = np.where(mask[:, :, None], dq_np, 0.0)
    np.testing.assert_allclose(scalar(diag, "dq_mean"),
                               dq_np.sum() / max(1, mask.sum() * A),
                               rtol=1e-3, atol=2e-5)

    # --- |TD| + IS-weight histograms: exact integer counts ------------
    (loss2, (prios2, aux)) = loss_and_priorities(
        cfg, net, params, state.target_params, batch, with_aux=True)
    td, mask2, _, max_abs_q = jax.device_get(aux)
    n = len(DIAG_SCALARS)
    td_counts = diag[n:n + len(TD_ABS_EDGES) + 1].astype(np.int64)
    np.testing.assert_array_equal(
        td_counts, np_bucketize(np.abs(td), mask2, TD_ABS_EDGES))
    is_counts = diag[n + len(TD_ABS_EDGES) + 1:].astype(np.int64)
    np.testing.assert_array_equal(
        is_counts, np_bucketize(batch_np["is_weights"],
                                np.ones(cfg.batch_size), IS_WEIGHT_EDGES))
    assert td_counts.sum() == mask2.sum()
    assert is_counts.sum() == cfg.batch_size
    np.testing.assert_allclose(
        scalar(diag, "td_abs_sum"),
        np.where(mask2, np.abs(td), 0.0).sum(), rtol=1e-5)
    np.testing.assert_allclose(scalar(diag, "max_abs_q"),
                               np.abs(q_stored).max(), rtol=1e-6)

    # --- norms: independent numpy recomputation -----------------------
    grad_fn = jax.value_and_grad(
        lambda p: loss_and_priorities(cfg, net, p, state.target_params,
                                      batch), has_aux=True)
    (_, _), grads = grad_fn(state.params)
    opt = make_optimizer(cfg)
    updates, _ = opt.update(grads, state.opt_state, state.params)

    def np_norm(tree):
        return np.sqrt(sum(
            float(np.square(np.asarray(leaf, np.float64)).sum())
            for leaf in jax.tree.leaves(jax.device_get(tree))))

    np.testing.assert_allclose(scalar(diag, "grad_norm"), np_norm(grads),
                               rtol=1e-5)
    np.testing.assert_allclose(scalar(diag, "update_norm"),
                               np_norm(updates), rtol=1e-5)
    np.testing.assert_allclose(scalar(diag, "param_norm"),
                               np_norm(new_state.params), rtol=1e-5)
    lag = jax.tree.map(lambda p, t: p - t, new_state.params,
                       new_state.target_params)
    np.testing.assert_allclose(scalar(diag, "target_lag"), np_norm(lag),
                               rtol=1e-4, atol=1e-7)


def test_diag_cadence_gating_and_disarmed_arity():
    """``lax.cond`` gating: armed exactly on multiples of the interval
    (the step counter advances in-graph); interval=0 compiles the
    3-tuple pre-learnhealth program — no diag output exists at all."""
    cfg = lh_config(learnhealth_interval=3)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    state = create_train_state(cfg, params)
    batch = {k: jnp.asarray(v)
             for k, v in synthetic_batch(cfg, A,
                                         np.random.default_rng(0)).items()}
    step = jax.jit(make_train_step(cfg, net, learnhealth=True))
    armed = []
    for _ in range(6):
        state, loss, prios, diag = step(state, batch)
        armed.append(scalar(diag, "armed"))
    assert armed == [0.0, 0.0, 1.0, 0.0, 0.0, 1.0]

    cfg0 = lh_config(learnhealth_interval=0)
    step0 = jax.jit(make_train_step(cfg0, create_network(cfg0, A),
                                    learnhealth=True))
    out = step0(create_train_state(cfg0, params), batch)
    assert len(out) == 3   # disarmed == the pre-learnhealth signature


def test_nan_sentry_counts_in_graph():
    """A poisoned batch (NaN n-step reward) must light the in-graph
    sentry: nonfinite > 0 in the armed diag."""
    cfg = lh_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    state = create_train_state(cfg, params)
    b = synthetic_batch(cfg, A, np.random.default_rng(0))
    b["n_step_reward"][0, 0] = np.nan
    batch = {k: jnp.asarray(v) for k, v in b.items()}
    _, loss, _, diag = jax.jit(make_train_step(cfg, net,
                                               learnhealth=True))(state,
                                                                  batch)
    assert not np.isfinite(float(loss))
    assert scalar(diag, "nonfinite") > 0


# ---------------------------------------------------------------------------
# monitor / engine / data-health units
# ---------------------------------------------------------------------------

def test_monitor_spike_ewma_and_freeze_interplay():
    """The loss-spike rule advances ONLY on loss samples: a stall/freeze
    produces no samples and therefore can never false-positive, while a
    genuine spike past factor×EWMA is counted once per spiking sample."""
    cfg = make_test_config(alert_loss_spike_factor=5.0)
    eng = AlertEngine(cfg, MetricsRegistry())
    mon = LearnHealthMonitor(cfg, engine=eng)
    mon.note_losses(np.full(30, 0.1))          # warmup, no spikes
    assert mon.snapshot()["loss_spikes"] == 0
    # a freeze = NO samples for a long wall-clock stretch: nothing to do
    mon.note_losses(np.full(5, 0.11))
    assert mon.snapshot()["loss_spikes"] == 0
    mon.note_losses(np.asarray([5.0]))         # 50x the EWMA
    snap = mon.snapshot()
    assert snap["loss_spikes"] == 1
    eng.evaluate(dict(learnhealth=snap))
    assert eng.counts().get("loss_spike") == 1
    # re-evaluating the same snapshot is idempotent (delta rule)
    eng.evaluate(dict(learnhealth=mon.snapshot()))
    assert eng.counts().get("loss_spike") == 1


def test_monitor_nonfinite_trips_and_fires_immediately():
    cfg = make_test_config()
    reg = MetricsRegistry()
    eng = AlertEngine(cfg, reg)
    mon = LearnHealthMonitor(cfg, engine=eng)
    assert not mon.tripped
    mon.note_losses(np.asarray([0.5, np.nan]))
    assert mon.tripped
    # fired at trip time, without any log-loop evaluate
    assert eng.counts()["nonfinite"] == 1
    assert eng.nonfinite_active
    assert reg.get_counter("learnhealth.alert", rule="nonfinite") == 1


def test_alert_engine_edge_rules_and_alertz(tmp_path):
    """ess_collapse / replay_ratio / dq_drift are EDGE rules (fire on
    the transition into violation); rows land in alerts.jsonl and the
    /alertz payload carries rules+counts+recent."""
    cfg = make_test_config(alert_ess_min=0.2, alert_replay_ratio_min=0.5,
                           alert_replay_ratio_max=2.0, alert_dq_budget=1.0)
    eng = AlertEngine(cfg, MetricsRegistry(), log_dir=str(tmp_path))
    healthy = dict(
        learnhealth=dict(nonfinite=0, loss_spikes=0, dq_mean=0.2),
        replay=dict(replay_ratio=1.0,
                    priorities=dict(ess_frac=0.9,
                                    positive_leaves=4 * cfg.batch_size)),
        training_steps=100)
    assert eng.evaluate(healthy) == []
    sick = dict(
        learnhealth=dict(nonfinite=0, loss_spikes=0, dq_mean=3.0),
        replay=dict(replay_ratio=7.0,
                    priorities=dict(ess_frac=0.01,
                                    positive_leaves=4 * cfg.batch_size)),
        training_steps=200)
    fired = {r["rule"] for r in eng.evaluate(sick)}
    assert fired == {"dq_drift", "ess_collapse", "replay_ratio"}
    # edge semantics: still in violation → no re-fire
    assert eng.evaluate(sick) == []
    assert set(eng.active()) == fired
    # recovery then relapse → one more fire each
    eng.evaluate(healthy)
    assert eng.active() == []
    assert {r["rule"] for r in eng.evaluate(sick)} == fired
    eng.close()

    rows = [json.loads(line) for line in
            (tmp_path / "alerts.jsonl").read_text().splitlines()]
    assert len(rows) == 6 and all(r["kind"] == "alert" for r in rows)
    assert all(r["threshold"] is not None for r in rows)

    status = eng.status()
    assert status["counts"] == {"dq_drift": 2, "ess_collapse": 2,
                                "replay_ratio": 2}
    assert {r["rule"] for r in status["rules"]} >= fired | {
        "nonfinite", "loss_spike"}
    assert len(status["recent"]) == 6

    # the exporter route contract: GET /alertz answers the status JSON
    from r2d2_tpu.telemetry.exporter import TelemetryExporter

    exp = TelemetryExporter(MetricsRegistry(), lambda: dict(ok=True),
                            routes={"/alertz": eng.route}, port=0)
    import threading

    t = threading.Thread(target=exp.handle_once, daemon=True)
    t.start()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/alertz", timeout=5) as resp:
        payload = json.loads(resp.read().decode())
    t.join(5)
    exp.close()
    assert payload["counts"]["replay_ratio"] == 2


def test_priority_health_oracle():
    leaves = np.asarray([0.0, 0.5, 0.5, 2.0, 0.0, 0.002])
    ph = priority_health(leaves)
    pos = leaves[leaves > 0]
    want_ess = pos.sum() ** 2 / np.square(pos).sum()
    assert ph["ess"] == pytest.approx(want_ess)
    assert ph["ess_frac"] == pytest.approx(want_ess / 4)
    assert ph["positive_leaves"] == 4
    assert sum(ph["hist"]) == 4
    np.testing.assert_array_equal(
        ph["hist"], np_bucketize(pos, np.ones_like(pos), PRIO_EDGES))
    empty = priority_health(np.zeros(8))
    assert empty["positive_leaves"] == 0 and empty["ess_frac"] == 1.0


def test_replay_buffer_data_health_and_member_fractions():
    """ESS/histogram over the live sum tree, the replay-ratio gauge, and
    per-member sampled-row counts riding the member_id stamp."""
    from r2d2_tpu.replay.block import LocalBuffer
    from r2d2_tpu.replay.replay_buffer import ReplayBuffer

    cfg = make_test_config(learning_starts=16)
    buf = ReplayBuffer(cfg, A, rng=np.random.default_rng(0))
    env = FakeAtariEnv(obs_shape=cfg.obs_shape, action_dim=A, seed=0,
                       episode_len=32)
    rng = np.random.default_rng(1)
    hidden = np.zeros((2, cfg.lstm_layers, cfg.hidden_dim), np.float32)
    member = 0
    while not buf.ready:
        lb = LocalBuffer(cfg, A)
        obs, _ = env.reset()
        lb.reset(obs)
        for _ in range(cfg.block_length):
            a = int(rng.integers(A))
            obs, r, _, _, _ = env.step(a)
            lb.add(a, float(r), obs,
                   rng.random(A).astype(np.float32), hidden)
        block, prios, _ = lb.finish(np.zeros(A, np.float32))
        block.member_id = member
        member = (member + 1) % 2
        buf.add(block, prios, None)
    dh = buf.data_health()
    assert dh["priorities"]["positive_leaves"] > 0
    assert 0 < dh["priorities"]["ess_frac"] <= 1.0
    assert sum(dh["priorities"]["hist"]) == \
        dh["priorities"]["positive_leaves"]
    assert dh["replay_ratio"] == 0.0      # nothing trained yet

    for _ in range(3):
        batch = buf.sample_batch(cfg.batch_size)
        buf.update_priorities(batch["idxes"],
                              np.ones(cfg.batch_size),
                              batch["block_ptr"], 0.1)
    dh = buf.data_health()
    spm = dh["samples_per_member"]
    assert sum(spm.values()) == 3 * cfg.batch_size
    assert set(spm) == {0, 1}             # both members actually sampled
    assert dh["replay_ratio"] == pytest.approx(replay_ratio(
        cfg, 3, buf.env_steps))


# ---------------------------------------------------------------------------
# HOST_TRANSFERS unchanged with diagnostics armed (the anakin budget)
# ---------------------------------------------------------------------------

def test_anakin_host_transfers_unchanged_with_diagnostics_armed():
    """The fused loop's crossing budget — ONE result fetch per dispatch
    — must hold with the learnhealth bundle armed (it rides the same
    flat vector), and the armed diag rows must actually reach the
    monitor."""
    from r2d2_tpu.learner.anakin import AnakinPlane
    from r2d2_tpu.learner.learner import Learner
    from r2d2_tpu.replay.device_ring import DeviceRing
    from r2d2_tpu.utils.trace import HOST_TRANSFERS, RETRACES

    cfg = make_test_config(
        game_name="Fake", actor_transport="anakin", device_replay=True,
        in_graph_per=True, num_actors=2, superstep_k=2,
        anakin_episode_len=12, training_steps=10 ** 9,
        learning_starts=16, learnhealth_interval=2)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    state = create_train_state(cfg, params)
    plane = AnakinPlane(cfg, net, A, DeviceRing(cfg, A))
    plane.monitor = LearnHealthMonitor(cfg)
    learner = Learner(cfg, net, state)
    while not plane.ready:
        plane.rollout_step(learner.state.params)

    before = HOST_TRANSFERS.get("anakin.result_fetch")
    dispatches = 5
    for _ in range(dispatches):
        learner.state, flat = plane.dispatch(learner.state)
        plane.harvest(flat)
    assert HOST_TRANSFERS.get("anakin.result_fetch") - before \
        == dispatches
    snap = plane.monitor.snapshot()
    # k=2, interval=2: one armed inner step per dispatch
    assert snap["armed_steps"] == dispatches
    assert snap["loss_count"] == dispatches * cfg.superstep_k
    assert snap["nonfinite"] == 0 and snap["dq_mean"] >= 0
    assert sum(snap["td_hist"]) > 0
    RETRACES.assert_within_budgets()


# ---------------------------------------------------------------------------
# e2e: NaN sentry, alerts.jsonl resume continuity
# ---------------------------------------------------------------------------

def test_nan_sentry_e2e_poisoned_params(tmp_path):
    """chaos ``poison_params`` mid-run: the run must fire the nonfinite
    alert (durable row + counter), flip /healthz to degraded, and stop
    CLEANLY (drain-then-save, no fabric failure / crashed thread)."""
    cfg = make_test_config(
        game_name="Fake", training_steps=10 ** 6, log_interval=0.2,
        learnhealth_interval=1, chaos_spec="poison_params:at=20")
    m = train(cfg, env_factory=env_factory, checkpoint_dir=str(tmp_path),
              verbose=False, max_wall_seconds=120)
    assert m["num_updates"] < 10 ** 6      # the trip stopped training
    assert not m["fabric_failed"]          # ... cleanly
    assert m["alerts"].get("nonfinite", 0) >= 1
    assert m["learnhealth"]["nonfinite"] > 0
    assert m["healthz"]["status"] == "degraded"
    assert m["healthz"]["degraded"] is True
    rows = read_alerts(str(tmp_path))
    assert any(r["rule"] == "nonfinite" for r in rows)
    # the drain-then-save epilogue still ran: a replay snapshot exists
    from r2d2_tpu.checkpoint import Checkpointer

    assert Checkpointer(str(tmp_path)).replay_steps()


def test_alerts_jsonl_resume_append_continuity(tmp_path):
    """A stop→resume cycle must APPEND to the same alerts.jsonl (RunLog
    conventions — the preemption story of every durable record): round
    2's rows land after round 1's, which stay byte-identical."""
    # a replay-ratio band the very first trained interval violates →
    # one deterministic fire per run
    cfg = make_test_config(
        game_name="Fake", training_steps=20, log_interval=0.2,
        learnhealth_interval=2, alert_replay_ratio_min=0.0,
        alert_replay_ratio_max=1e-6)
    m1 = train(cfg, env_factory=env_factory, checkpoint_dir=str(tmp_path),
               verbose=False, max_wall_seconds=120)
    assert m1["alerts"].get("replay_ratio", 0) >= 1
    path = tmp_path / "telemetry" / "alerts.jsonl"
    round1 = path.read_text()
    rows1 = read_alerts(str(tmp_path))
    assert rows1

    m2 = train(cfg.replace(training_steps=40), env_factory=env_factory,
               checkpoint_dir=str(tmp_path), resume=True, verbose=False,
               max_wall_seconds=120)
    assert m2["alerts"].get("replay_ratio", 0) >= 1
    content = path.read_text()
    assert content.startswith(round1)      # append-only continuity
    rows2 = read_alerts(str(tmp_path))
    assert len(rows2) > len(rows1)


def test_train_e2e_diagnostics_and_no_false_alerts(tmp_path):
    """A healthy threaded run with every rule armed (wide thresholds):
    diagnostics flow (armed steps, ΔQ, histograms, replay health on the
    entries and the registry) and ZERO alerts fire."""
    cfg = make_test_config(
        game_name="Fake", training_steps=30, log_interval=0.2,
        learnhealth_interval=2, alert_ess_min=0.001,
        alert_replay_ratio_max=1e6, alert_dq_budget=1e6,
        telemetry_port=-1)
    m = train(cfg, env_factory=env_factory, checkpoint_dir=str(tmp_path),
              verbose=False, max_wall_seconds=120)
    assert m["num_updates"] >= 30
    assert m["alerts"] == {}
    lh = m["learnhealth"]
    assert lh["armed_steps"] >= m["num_updates"] // 2 - 1
    assert lh["dq_mean"] > 0 and lh["grad_norm"] > 0
    assert sum(lh["td_hist"]) > 0 and sum(lh["is_hist"]) > 0
    entries = [e for e in m["logs"] if e.get("learnhealth")]
    assert entries
    last = entries[-1]
    assert last["alerts"] == {}
    assert last["replay_health"]["priorities"]["positive_leaves"] > 0
    assert read_alerts(str(tmp_path)) == []
    # the console line renders the ΔQ diagnostic
    from r2d2_tpu.telemetry import format_entry

    assert "dq=" in format_entry(last)
    # registry absorption: gauges + the declared histograms landed
    reg = None  # metrics carry no registry; assert via a fresh record
    from r2d2_tpu.telemetry.plane import Telemetry

    tel = Telemetry(cfg)
    tel.record(last)
    reg = tel.registry
    assert reg.get_gauge("learnhealth.dq_mean") > 0
    assert reg.get_counter("learnhealth.armed_steps") > 0
    snap = reg.snapshot()
    assert "learnhealth.td_abs" in snap["histograms"]
    assert "learnhealth.is_weight" in snap["histograms"]
    assert any(k.startswith("learnhealth.replay.ess")
               for k in snap["gauges"])
