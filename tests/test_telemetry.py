"""Telemetry plane (ISSUE 5): registry semantics, cross-process slab
merge, JSONL run-log durability, exporter endpoint contracts, and the
train() acceptance e2es (fleet-aggregated /metrics, /healthz flipping on
a chaos-stalled heartbeat, SIGTERM→resume continuity of run.jsonl, the
bounded in-memory logs ring).
"""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.envs.fake import FakeAtariEnv
from r2d2_tpu.telemetry import (
    CounterMerger,
    MetricsRegistry,
    RunLog,
    Telemetry,
    TelemetryExporter,
    format_entry,
    make_exporter,
    read_entries,
    tail_entry,
)
from r2d2_tpu.telemetry.slab import StatsSlab, StatsSlabWriter
from r2d2_tpu.train import train

A = 4


def env_factory(cfg, seed):
    return FakeAtariEnv(obs_shape=cfg.obs_shape, action_dim=A, seed=seed,
                        episode_len=32)


# ------------------------------------------------------------- registry

def test_registry_counters_gauges_labels():
    r = MetricsRegistry()
    r.inc("ingest.blocks", 2)
    r.inc("ingest.blocks", 3)
    r.inc("ingest.blocks", 1, fleet="0")
    assert r.get_counter("ingest.blocks") == 5
    assert r.get_counter("ingest.blocks", fleet="0") == 1
    with pytest.raises(ValueError, match="negative"):
        r.inc("ingest.blocks", -1)
    r.set_gauge("fill", 7.0)
    r.set_gauge("fill", 3.0)
    assert r.get_gauge("fill") == 3.0
    snap = r.snapshot()
    assert snap["counters"]["ingest.blocks"] == 5
    assert snap["counters"]["ingest.blocks{fleet=0}"] == 1
    assert snap["gauges"]["fill"] == 3.0


def test_registry_counter_max_is_monotone_and_idempotent():
    """The absorption path for absolute external counters: re-absorbing
    the same snapshot changes nothing, and a restarted source (smaller
    value) can never drag the series backwards."""
    r = MetricsRegistry()
    r.counter_max("steps", 10)
    r.counter_max("steps", 10)
    assert r.get_counter("steps") == 10
    r.counter_max("steps", 4)      # restarted source
    assert r.get_counter("steps") == 10
    r.counter_max("steps", 12)
    assert r.get_counter("steps") == 12


def test_histogram_bucket_math_against_numpy_oracle():
    """Fixed-bucket counts must match a numpy histogram over the same
    (inclusive-upper-bound) edges, and the rendered cumulative buckets
    must be the running sum."""
    bounds = [0.5, 1.0, 2.0, 8.0]
    rng = np.random.default_rng(0)
    values = np.concatenate([rng.uniform(0, 10, 500), np.asarray(bounds)])
    r = MetricsRegistry()
    r.declare_histogram("lat", bounds)
    for v in values:
        r.observe("lat", float(v))
    h = r.snapshot()["histograms"]["lat"]
    # oracle: bucket i counts values in (bounds[i-1], bounds[i]]
    edges = np.concatenate([[-np.inf], bounds, [np.inf]])
    oracle, _ = np.histogram(values, bins=edges)
    # np.histogram's bins are half-open [lo, hi) except the last; our
    # buckets are (lo, hi] — values AT an edge differ. Count directly:
    direct = []
    prev = -np.inf
    for b in list(bounds) + [np.inf]:
        direct.append(int(((values > prev) & (values <= b)).sum()))
        prev = b
    assert h["counts"] == direct
    assert h["count"] == len(values)
    assert np.isclose(h["sum"], values.sum())
    # rendered cumulative le buckets are the running sum
    txt = r.render_prometheus()
    cums = [int(line.rsplit(" ", 1)[1]) for line in txt.splitlines()
            if line.startswith("r2d2_lat_bucket")]
    assert cums == list(np.cumsum(direct))
    assert cums[-1] == len(values)


def test_prometheus_rendering_contract():
    r = MetricsRegistry()
    r.inc("a.b", 2, path='we"ird\\lab\nel')
    r.set_gauge("g", float("nan"))
    txt = r.render_prometheus()
    assert "# TYPE r2d2_a_b_total counter" in txt
    # label escaping: backslash, quote, newline
    assert r'path="we\"ird\\lab\nel"' in txt
    assert "r2d2_g NaN" in txt
    assert txt.endswith("\n")


# ------------------------------------------------- cross-process slab

def test_stats_slab_roundtrip_and_crc_rejects_garble():
    slab = StatsSlab(2)
    w = StatsSlabWriter(slab.writer_info(0))
    try:
        assert slab.read(0) is None          # never published
        w.publish(dict(env_steps=10, blocks_produced=2, incarnation=0))
        seq, values = slab.read(0)
        assert seq == 1 and values[0] == 10
        # garble a byte inside slot 0: the CRC gate must reject it
        buf = np.frombuffer(slab.shm.buf, np.uint8)
        buf[8] ^= 0xFF
        assert slab.read(0) is None
        buf[8] ^= 0xFF                       # restore -> valid again
        assert slab.read(0) is not None
        del buf         # release the exported view before slab.close()
    finally:
        w.close()
        slab.close()
    assert slab.read(0) is None              # closed slab reads None


def test_counter_merge_monotone_across_respawn():
    """THE merge-semantics oracle: counters summed across fleets must
    never regress through a respawn (fresh process, counters restart at
    zero, publish seq restarts, incarnation bumps) — including counters
    that legally decrease in value (negative reward sums)."""
    slab = StatsSlab(2)
    m = CounterMerger(2)

    def publish_and_merge(writer, slot, **stats):
        writer.publish(stats)
        m.update(slot, *slab.read(slot))
        return m.totals()

    w0 = StatsSlabWriter(slab.writer_info(0))
    w1 = StatsSlabWriter(slab.writer_info(1))
    try:
        publish_and_merge(w0, 0, env_steps=100, episode_reward_sum=-5.0,
                          incarnation=0)
        t = publish_and_merge(w1, 1, env_steps=40, episode_reward_sum=-1.0,
                              incarnation=0)
        assert t["env_steps"] == 140 and t["episode_reward_sum"] == -6.0
        # fleet 1 respawns: new writer, counters AND seq restart at zero
        w1b = StatsSlabWriter(slab.writer_info(1))
        t2 = publish_and_merge(w1b, 1, env_steps=7,
                               episode_reward_sum=-0.5, incarnation=1)
        w1b.close()
        assert t2["env_steps"] == 147          # 100 + (40 folded + 7)
        assert t2["episode_reward_sum"] == -6.5
        assert m.incarnations() == [0, 1]
        # idempotent re-read of the same seq
        m.update(1, *slab.read(1))
        assert m.totals()["env_steps"] == 147
        # monotone within an incarnation too
        w0.publish(dict(env_steps=120, episode_reward_sum=-9.0,
                        incarnation=0))
        m.update(0, *slab.read(0))
        assert m.totals()["env_steps"] == 167
    finally:
        w0.close()
        w1.close()
        slab.close()


def test_counter_merge_seq_regression_fold_without_incarnation_field():
    """A schema without the incarnation field still folds on a seq
    regression (producer restarted outside the watchdog)."""
    fields = (("n", "counter"),)
    m = CounterMerger(1, fields)
    m.update(0, 5, np.asarray([10.0]))
    m.update(0, 1, np.asarray([3.0]))      # seq regressed: new stream
    assert m.totals()["n"] == 13.0


def test_counter_merge_seq_regression_fold_with_same_incarnation():
    """A producer restart that does NOT bump the incarnation (restarted
    outside the watchdog) must still fold on the seq regression — the
    incarnation field must not mask it."""
    fields = (("n", "counter"), ("incarnation", "gauge"))
    m = CounterMerger(1, fields)
    m.update(0, 50, np.asarray([10_000.0, 0.0]))
    m.update(0, 1, np.asarray([3.0, 0.0]))   # same inc, seq restarted
    assert m.totals()["n"] == 10_003.0
    # and the fresh stream keeps accumulating normally
    m.update(0, 2, np.asarray([7.0, 0.0]))
    assert m.totals()["n"] == 10_007.0


def test_record_exports_negative_reward_sum_as_gauge_not_counter():
    """Reward sums legally go negative and decrease; routing them
    through the counter path would clamp at the historical max and
    never export a negative value at all."""
    t = Telemetry(make_test_config())
    fleet = dict(stats=dict(totals=dict(env_steps=100, episodes=3,
                                        blocks_produced=5,
                                        episode_reward_sum=-42.0),
                            per_fleet=[dict(env_steps=100,
                                            episode_reward_sum=-42.0,
                                            param_version=2)]))
    t.record(dict(training_steps=5, env_steps=90, fleet=fleet))
    reg = t.registry
    assert reg.get_counter("actor.env_steps") == 100
    assert reg.get_gauge("actor.episode_reward_sum") == -42.0
    assert reg.get_gauge("actor.fleet.episode_reward_sum",
                         fleet="0") == -42.0
    # and it tracks a further decrease (a counter_max never would)
    fleet["stats"]["totals"]["episode_reward_sum"] = -50.0
    t.record(dict(training_steps=6, env_steps=95, fleet=fleet))
    assert reg.get_gauge("actor.episode_reward_sum") == -50.0


def test_record_absorbs_anakin_eval_lane_and_gates_nan_gauge():
    """The anakin entry's in-graph eval-lane fields (ISSUE 15) land in
    the registry — `anakin.eval_episodes` as a counter, `eval_return`
    as a gauge that stays ABSENT while the plane's last_eval_return is
    still NaN (pre-first-eval): a NaN gauge would poison /metrics
    parsers."""
    t = Telemetry(make_test_config())
    an = dict(super_steps=4, frames=64, frames_per_sec=10.0, blocks=2,
              episodes_total=1, eval_episodes=0,
              eval_return=float("nan"))
    t.record(dict(training_steps=2, env_steps=64, buffer_size=8,
                  anakin=an))
    reg = t.registry
    assert reg.get_counter("anakin.eval_episodes") == 0
    assert reg.get_gauge("anakin.eval_return") is None
    an.update(eval_episodes=8, eval_return=17.5)
    t.record(dict(training_steps=4, env_steps=128, buffer_size=8,
                  anakin=an))
    assert reg.get_counter("anakin.eval_episodes") == 8
    assert reg.get_gauge("anakin.eval_return") == 17.5


# --------------------------------------------------------- JSONL run log

def test_runlog_append_resume_and_rotation(tmp_path):
    d = str(tmp_path / "tele")
    log = RunLog(d, max_bytes=1024, keep=2)
    for i in range(30):
        log.append(dict(training_steps=i, pad="x" * 80))
    log.close()
    # rotation: bounded active file, rotated segments present
    assert os.path.getsize(log.path) <= 1024
    assert os.path.exists(log.path + ".1")
    # resume: a new RunLog on the same dir APPENDS (never truncates)
    log2 = RunLog(d, max_bytes=1024, keep=2)
    log2.append(dict(training_steps=30))
    log2.close()
    entries = list(read_entries(log2.path))
    steps = [e["training_steps"] for e in entries]
    assert steps == sorted(steps), "rotated read must be oldest-first"
    assert steps[-1] == 30
    # keep budget: at most `keep` rotated segments
    k = 1
    while os.path.exists(f"{log2.path}.{k}"):
        k += 1
    assert k - 1 <= 2


def test_runlog_torn_final_line_and_tail(tmp_path):
    d = str(tmp_path / "tele")
    log = RunLog(d)
    log.append(dict(a=1))
    log.append(dict(a=2))
    log.close()
    with open(log.path, "a", encoding="utf-8") as fh:
        fh.write('{"a": 3, "torn": tru')     # kill -9 mid-write
    assert [e["a"] for e in read_entries(log.path)] == [1, 2]
    assert tail_entry(log.path)["a"] == 2


# ------------------------------------------------------------- exporter

def test_supervisor_giveup_flips_healthz_and_stamps_registry():
    """ISSUE 7 satellite: the supervisor give-up path.  A thread that
    exhausts its restart budget must (a) flip the /healthz verdict to
    503 via ``Supervisor.any_failed`` and (b) stamp ``supervisor.gaveup``
    into the registry through the supervisor's own on_giveup hook — the
    exact wiring train() installs (the log loop, the usual absorption
    path, may be the very thread that died)."""
    import urllib.error
    import urllib.request

    from r2d2_tpu.utils.supervisor import Supervisor

    reg = MetricsRegistry()
    sup = Supervisor(max_restarts=0, backoff=0.01,
                     on_giveup=lambda name: reg.inc("supervisor.gaveup",
                                                    thread=name))

    def doomed_loop():
        raise RuntimeError("plane down")

    sup.start("doomed", doomed_loop)
    deadline = time.time() + 30
    while not sup.any_failed:
        assert time.time() < deadline, "supervisor never gave up"
        time.sleep(0.02)

    # the same three-state healthz shape train() serves
    def healthz():
        ok = not sup.any_failed
        return dict(ok=ok, degraded=False,
                    status="ok" if ok else "failing",
                    threads=sup.health())

    ex = TelemetryExporter(reg, healthz, port=0)
    _serve(ex)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{ex.port}/healthz")
        assert e.value.code == 503
        body = json.loads(e.value.read())
        assert body["status"] == "failing"
        assert body["threads"]["doomed"]["gave_up"]
        # the registry stamp (scrapeable on /metrics)
        assert reg.get_counter("supervisor.gaveup", thread="doomed") == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ex.port}/metrics") as resp:
            assert "r2d2_supervisor_gaveup_total" in resp.read().decode()
    finally:
        ex.close()
    # record() absorption is the belt: a health entry with a gave-up
    # thread stamps the counter even without the callback wiring
    t = Telemetry(make_test_config())
    t.record(dict(training_steps=1, env_steps=1,
                  health={"pump": dict(alive=False, restarts=3,
                                       gave_up=True)}))
    assert t.registry.get_counter("supervisor.gaveup", thread="pump") == 1


@pytest.mark.timeout(600)
def test_train_e2e_supervisor_giveup_stops_fabric():
    """A fabric thread dying past its restart budget must end the run
    with fabric_failed and the give-up visible in the returned health —
    not hang the trainer (the reference would simply starve forever)."""
    cfg = make_test_config(game_name="Fake", training_steps=100000,
                           log_interval=0.1)
    calls = []

    def poisoned_sink(entry):
        calls.append(entry)
        raise RuntimeError("log plane poisoned")

    m = train(cfg, env_factory=env_factory, verbose=False,
              log_sink=poisoned_sink, max_thread_restarts=0,
              max_wall_seconds=180)
    assert calls, "the log loop never ran"
    assert m["fabric_failed"] is True
    assert m["health"]["log"]["gave_up"] is True
    assert m["num_updates"] < 100000      # the give-up stopped the run


def test_exporter_disabled_at_port_zero():
    cfg = make_test_config()                 # telemetry_port defaults 0
    assert cfg.telemetry_port == 0
    assert make_exporter(cfg, MetricsRegistry(), lambda: {"ok": True}) \
        is None


def _serve(ex):
    def loop():
        while not ex.closed:
            try:
                ex.handle_once()
            except (OSError, ValueError):   # closed under a late poll
                return

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def test_exporter_endpoint_contracts():
    r = MetricsRegistry()
    r.inc("a.b", 1, q='x"y')
    health = {"ok": True, "detail": "fine"}
    ex = TelemetryExporter(r, lambda: dict(health), port=0)
    _serve(ex)
    base = f"http://127.0.0.1:{ex.port}"
    try:
        with urllib.request.urlopen(base + "/metrics") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == \
                "text/plain; version=0.0.4; charset=utf-8"
            body = resp.read().decode()
        assert "r2d2_a_b_total" in body and r'q="x\"y"' in body
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "application/json")
            assert json.loads(resp.read())["ok"] is True
        with urllib.request.urlopen(base + "/statusz") as resp:
            status = json.loads(resp.read())
        assert status["metrics"]["counters"]['a.b{q=x"y}'] == 1
        assert status["health"]["detail"] == "fine"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/nope")
        assert e.value.code == 404
        # non-OK health -> 503 with the JSON verdict in the body
        health["ok"] = False
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["ok"] is False
    finally:
        ex.close()


# ------------------------------------------------- console / r2d2_top

def test_console_formatting_shared_with_top():
    entry = dict(training_steps=12, updates_per_sec=3.0, buffer_size=64,
                 env_steps=999, mean_episode_return=1.5, mean_loss=0.25,
                 fleet=dict(alive=2, fleets=2, restarts=[0, 1],
                            blocks_ingested=5, blocks_corrupt=0,
                            stats=dict(totals=dict(env_steps=800))))
    line = format_entry(entry)
    assert "updates=12" in line and "env_steps=999" in line
    assert "fleets=2/2" in line and "fleet_env_steps=800" in line

    import importlib.util

    top_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "r2d2_top.py")
    spec = importlib.util.spec_from_file_location("r2d2_top", top_path)
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    frame = top.render(entry, health=dict(ok=False, threads={}))
    assert line in frame          # the SAME formatting path
    assert "NOT OK" in frame
    assert top.render({}) == "[r2d2] (no telemetry yet)"


# ------------------------------------------------------ train() e2es

# slow: ~25 s process-transport run on the tier-1 wall budget (ISSUE 15
# rebalance).  The merge/absorption/exporter claims stay pinned by the
# unit layer above; every remaining train() e2e exercises registry +
# JSONL absorption on its own transport.
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_train_e2e_metrics_endpoint_aggregates_fleet_counters(tmp_path):
    """Acceptance: a train() run with telemetry enabled serves /metrics
    whose actor env-step counter is the SUM across subprocess fleets
    (each fleet publishing through the stats slab), with per-fleet
    labeled series alongside."""
    from test_actor_procs import make_fake_env

    cfg = make_test_config(game_name="Fake", training_steps=2000,
                           num_actors=2, actor_fleets=2,
                           actor_transport="process",
                           log_interval=0.2, telemetry_port=-1)
    seen = dict(port=0, scraped=None)

    def sink(entry):
        seen["port"] = entry["telemetry_port"]
        stats = (entry.get("fleet") or {}).get("stats", {})
        # wait until EVERY fleet has published through the slab at least
        # once — scraping on the first fleet's publish races the second
        # fleet's spawn and finds only one labeled series
        rows = stats.get("per_fleet", [])
        if seen["scraped"] is None and len(rows) == 2 and all(
                r.get("env_steps", 0) > 0 for r in rows):
            base = f"http://127.0.0.1:{seen['port']}"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                seen["scraped"] = resp.read().decode()
            os.kill(os.getpid(), signal.SIGTERM)   # scraped: end the run

    m = train(cfg, env_factory=make_fake_env, checkpoint_dir=None,
              verbose=False, log_sink=sink, max_wall_seconds=300)
    assert seen["scraped"] is not None, "fleet stats never aggregated"
    series = {}
    for line in seen["scraped"].splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        series[name] = float(value)
    agg = series.get("r2d2_actor_env_steps_total", 0)
    per_fleet = [v for k, v in series.items()
                 if k.startswith("r2d2_actor_fleet_env_steps_total{")]
    assert agg > 0
    assert len(per_fleet) == 2               # one labeled series per fleet
    assert agg == sum(per_fleet)
    assert m["telemetry_port"] == seen["port"] > 0


@pytest.mark.timeout(600)
def test_train_e2e_healthz_flips_on_chaos_frozen_learner():
    """Acceptance: the chaos freeze_learner site stalls the heartbeat;
    /healthz must flip to 503/ok=False while the learner is frozen (the
    exporter outlives the fabric stop precisely for this), and the run
    must end with learner_stalled set by the watchdog."""
    cfg = make_test_config(game_name="Fake", training_steps=100000,
                           log_interval=0.2, telemetry_port=-1,
                           learner_stall_timeout=1.5,
                           chaos_spec="freeze_learner:at=1,dur=10")
    port_q = []
    result = {}

    def sink(entry):
        if not port_q:
            port_q.append(entry["telemetry_port"])

    def run():
        result["m"] = train(cfg, env_factory=env_factory, verbose=False,
                            log_sink=sink, max_wall_seconds=300)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 120
    while not port_q:
        assert time.time() < deadline, "no log entry with the port"
        assert t.is_alive() or "m" in result
        time.sleep(0.05)
    base = f"http://127.0.0.1:{port_q[0]}"
    flipped = None
    while time.time() < deadline and flipped is None:
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=5) as resp:
                assert resp.status == 200    # healthy (pre-stall)
        except urllib.error.HTTPError as e:
            assert e.code == 503
            flipped = json.loads(e.read())
        except OSError:
            break                            # run ended: exporter gone
        time.sleep(0.1)
    t.join(300)
    assert flipped is not None, "/healthz never went non-OK"
    assert flipped["ok"] is False and flipped["learner_stalled"] is True
    assert result["m"]["learner_stalled"] is True


@pytest.mark.timeout(600)
def test_train_e2e_sigterm_resume_one_continuous_runlog(tmp_path):
    """Acceptance: SIGTERM a run mid-stream, resume it — run.jsonl is
    ONE appended file whose training_steps curve continues monotonically
    across the restart (never truncated), readable end to end."""
    ck = str(tmp_path / "ck")
    cfg = make_test_config(game_name="Fake", training_steps=100000,
                           log_interval=0.2, save_interval=10 ** 8)

    def sink(entry):
        if entry["training_steps"] >= 10:
            os.kill(os.getpid(), signal.SIGTERM)

    m1 = train(cfg, env_factory=env_factory, checkpoint_dir=ck,
               verbose=False, log_sink=sink, max_wall_seconds=180)
    assert 0 < m1["num_updates"] < 100000
    path = os.path.join(ck, "telemetry", "run.jsonl")
    first = [e["training_steps"] for e in read_entries(path)]
    assert first and first == sorted(first)

    m2 = train(cfg.replace(training_steps=m1["num_updates"] + 4),
               env_factory=env_factory, checkpoint_dir=ck, resume=True,
               verbose=False, max_wall_seconds=180)
    assert m2["restored_replay"]
    assert not os.path.exists(path + ".1"), "resume must append, not rotate"
    steps = [e["training_steps"] for e in read_entries(path)]
    assert len(steps) > len(first)           # the resumed run appended
    assert steps == sorted(steps), \
        "training_steps must continue monotonically across the restart"


@pytest.mark.timeout(600)
def test_train_logs_ring_capped_under_fast_log_interval(tmp_path):
    """Acceptance: with log_interval≈0 the in-memory logs list is a
    cfg.log_history_cap ring (the old unbounded list), while the JSONL
    run log keeps every entry."""
    ck = str(tmp_path / "ck")
    cfg = make_test_config(game_name="Fake", training_steps=40,
                           log_interval=0.01, log_history_cap=16,
                           save_interval=10 ** 8)
    m = train(cfg, env_factory=env_factory, checkpoint_dir=ck,
              verbose=False, max_wall_seconds=180)
    assert m["num_updates"] == 40
    assert len(m["logs"]) == 16              # ring is full AND capped
    path = os.path.join(ck, "telemetry", "run.jsonl")
    total = sum(1 for _ in read_entries(path))
    assert total > 16, "JSONL must retain what the ring evicted"
    # the ring holds the NEWEST entries (same tail as the file)
    tail = [e["time"] for e in read_entries(path)][-16:]
    assert [e["time"] for e in m["logs"]] == tail
