import jax
import jax.numpy as jnp
import numpy as np
import pytest

from r2d2_tpu.config import test_config as make_test_config
from r2d2_tpu.learner.step import (
    TrainState, create_train_state, loss_and_priorities,
    _window_indices, value_rescale, inverse_value_rescale,
)
from r2d2_tpu.models.network import R2D2Network, create_network, init_params
from r2d2_tpu.parallel.sharding import pjit_train_step
from r2d2_tpu.utils import math as hmath

A = 4


def reference_target_indices(b, l, f, n):
    """The reference's target-window construction (model.py:102-109): slice
    [b+n : b+l+f], then edge-pad min(n-f, l) copies of the final element."""
    idxs = list(range(b + n, b + l + f))
    pad = min(n - f, l)
    idxs = idxs + [b + l + f - 1] * pad
    return idxs[:l]


def test_window_indices_match_reference_semantics():
    cfg = make_test_config()  # L=4, n=2
    n, L = cfg.forward_steps, cfg.learning_steps
    cases = []
    for b in range(0, cfg.burn_in_steps + 1):
        for l in range(1, L + 1):
            for f in range(1, n + 1):
                cases.append((b, l, f))
    burn = jnp.array([c[0] for c in cases])
    learn = jnp.array([c[1] for c in cases])
    fwd = jnp.array([c[2] for c in cases])
    idx_online, idx_target, mask = _window_indices(cfg, burn, learn, fwd)
    for row, (b, l, f) in enumerate(cases):
        expected_online = [b + i for i in range(l)]
        expected_target = reference_target_indices(b, l, f, n)
        got_online = np.asarray(idx_online[row])[:l].tolist()
        got_target = np.asarray(idx_target[row])[:l].tolist()
        assert got_online == expected_online, (b, l, f)
        assert got_target == expected_target, (b, l, f)
        assert np.asarray(mask[row]).sum() == l


def test_value_rescale_matches_numpy():
    x = jnp.linspace(-100, 100, 201)
    np.testing.assert_allclose(np.asarray(value_rescale(x)),
                               hmath.value_rescale(np.asarray(x)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(inverse_value_rescale(x)),
                               hmath.inverse_value_rescale(np.asarray(x)),
                               rtol=1e-5, atol=1e-5)


def make_batch(cfg, rng, B):
    T, L = cfg.seq_len, cfg.learning_steps
    n = cfg.forward_steps
    learning = rng.integers(1, L + 1, B).astype(np.int32)
    burn_in = rng.integers(0, cfg.burn_in_steps + 1, B).astype(np.int32)
    forward = np.where(learning == L, rng.integers(1, n + 1, B), 1).astype(np.int32)
    return dict(
        obs=rng.integers(0, 255, (B, T, *cfg.obs_shape), dtype=np.uint8),
        last_action=rng.random((B, T, A)).astype(np.float32),
        last_reward=rng.random((B, T)).astype(np.float32),
        hidden=rng.normal(size=(B, 2, cfg.lstm_layers, cfg.hidden_dim)).astype(np.float32),
        action=rng.integers(0, A, (B, L)).astype(np.int32),
        n_step_reward=rng.normal(size=(B, L)).astype(np.float32),
        n_step_gamma=np.full((B, L), cfg.gamma ** n, np.float32),
        burn_in=burn_in, learning=learning, forward=forward,
        is_weights=rng.uniform(0.2, 1.0, B).astype(np.float32),
    )


def numpy_oracle(cfg, net, params, target_params, batch):
    """Reference learner semantics (worker.py:344-359) recomputed with plain
    numpy ragged loops on top of the network's unrolled Q sequences."""
    to_j = lambda x: jnp.asarray(x)
    q_online, _ = net.apply(params, to_j(batch["obs"]), to_j(batch["last_action"]),
                            to_j(batch["last_reward"]), to_j(batch["hidden"]),
                            method=R2D2Network.unroll)
    q_target, _ = net.apply(target_params, to_j(batch["obs"]),
                            to_j(batch["last_action"]), to_j(batch["last_reward"]),
                            to_j(batch["hidden"]), method=R2D2Network.unroll)
    q_online, q_target = np.asarray(q_online), np.asarray(q_target)

    B = q_online.shape[0]
    n = cfg.forward_steps
    total_loss, total_count = 0.0, 0
    td_all, ls_all = [], []
    for i in range(B):
        b, l, f = int(batch["burn_in"][i]), int(batch["learning"][i]), int(batch["forward"][i])
        tgt_idx = reference_target_indices(b, l, f, n)
        q_taken = q_online[i, b:b + l, :][np.arange(l), batch["action"][i, :l]]
        a_star = q_online[i, tgt_idx, :].argmax(-1)
        q_boot = q_target[i, tgt_idx, :][np.arange(l), a_star]
        target = hmath.value_rescale(
            batch["n_step_reward"][i, :l]
            + batch["n_step_gamma"][i, :l] * hmath.inverse_value_rescale(q_boot))
        td = target - q_taken
        total_loss += (batch["is_weights"][i] * td ** 2).sum()
        total_count += l
        td_all.append(np.abs(td))
        ls_all.append(l)
    loss = total_loss / total_count
    prios = hmath.mixed_td_errors(np.concatenate(td_all).astype(np.float32),
                                  np.array(ls_all))
    return loss, prios


def test_loss_and_priorities_match_reference_oracle():
    cfg = make_test_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    target_params = init_params(cfg, net, jax.random.PRNGKey(1))
    rng = np.random.default_rng(7)
    batch = make_batch(cfg, rng, B=8)

    loss, prios = loss_and_priorities(
        cfg, net, params, target_params,
        {k: jnp.asarray(v) for k, v in batch.items()})
    exp_loss, exp_prios = numpy_oracle(cfg, net, params, target_params, batch)

    np.testing.assert_allclose(float(loss), exp_loss, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(prios), exp_prios, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_fused_double_unroll_matches_unfused():
    """cfg.fused_double_unroll (one vmapped unroll over stacked
    online+target params) must be a pure scheduling change: identical
    loss, priorities, AND parameter gradients to the two-unroll path."""
    cfg = make_test_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(0))
    target_params = init_params(cfg, net, jax.random.PRNGKey(1))
    rng = np.random.default_rng(11)
    batch = {k: jnp.asarray(v) for k, v in make_batch(cfg, rng, B=8).items()}

    fused_cfg = cfg.replace(fused_double_unroll=True)

    def run(c):
        def loss_fn(p):
            return loss_and_priorities(c, net, p, target_params, batch)

        (loss, prios), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, prios, grads

    loss_a, prios_a, grads_a = run(cfg)
    loss_b, prios_b, grads_b = run(fused_cfg)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(prios_a), np.asarray(prios_b),
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads_a, grads_b)
    # no gradient leaks into the target side: the fused stack must not
    # create a path around the stop_gradient
    gnorm = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads_b))
    assert np.isfinite(gnorm) and gnorm > 0


def test_train_step_reduces_loss_and_syncs_target():
    cfg = make_test_config(target_net_update_interval=5)
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(2))
    state = create_train_state(cfg, params)
    # the ONE train-step entry point (trivial 1-device mesh); host numpy
    # batches — the step donates its batch arg, so a device batch could
    # not be re-stepped
    step_fn = pjit_train_step(cfg, net, state_template=state)
    rng = np.random.default_rng(8)
    batch = make_batch(cfg, rng, B=8)

    losses = []
    for i in range(10):
        state, loss, prios = step_fn(state, batch)
        losses.append(float(loss))
        assert np.isfinite(losses[-1])
        assert np.asarray(prios).shape == (8,)
        if i + 1 == 5:
            # hard sync just happened (step counter == interval)
            diff = jax.tree.map(lambda p, t: float(jnp.abs(p - t).max()),
                                state.params, state.target_params)
            assert max(jax.tree.leaves(diff)) == 0.0
    assert losses[-1] < losses[0]
    assert int(state.step) == 10


def test_gradients_do_not_flow_into_target_selection():
    """Value semantics check: perturbing target params changes loss, but the
    double-Q argmax path must be stop-gradiented — grads wrt target params of
    the loss are identically zero."""
    cfg = make_test_config()
    net = create_network(cfg, A)
    params = init_params(cfg, net, jax.random.PRNGKey(3))
    target_params = init_params(cfg, net, jax.random.PRNGKey(4))
    rng = np.random.default_rng(9)
    batch = {k: jnp.asarray(v) for k, v in make_batch(cfg, rng, B=4).items()}

    def loss_wrt_target(tp):
        loss, _ = loss_and_priorities(cfg, net, params, tp, batch)
        return loss

    grads = jax.grad(loss_wrt_target)(target_params)
    assert max(jax.tree.leaves(jax.tree.map(
        lambda g: float(jnp.abs(g).max()), grads))) == 0.0


def test_published_snapshot_survives_state_donation():
    """Learner._publish's one-dispatch jitted tree-copy must produce
    buffers genuinely distinct from the (donated) train state: a later
    step reusing the donated buffers must not clobber what actors hold."""
    import jax
    import jax.numpy as jnp

    copy_fn = jax.jit(lambda p: jax.tree.map(jnp.copy, p))
    x = {"w": jnp.arange(8, dtype=jnp.float32)}
    snap = copy_fn(x)
    step = jax.jit(lambda p: jax.tree.map(lambda a: a * 0 - 1, p),
                   donate_argnums=0)
    step(x)  # donates x's buffers — snap must be unaffected
    np.testing.assert_array_equal(np.asarray(snap["w"]),
                                  np.arange(8, dtype=np.float32))
